"""Exception hierarchy.

Analog of the reference's ``ray.exceptions`` (`python/ray/exceptions.py`):
user-visible failure types for tasks, actors and objects.
"""

from __future__ import annotations

import traceback
from typing import Optional


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at ray_tpu.get().

    Carries the remote traceback string so the user sees the real failure
    site, matching the reference's RayTaskError formatting.
    """

    def __init__(self, function_name: str, cause: Exception | None, tb_str: str = ""):
        self.function_name = function_name
        self.cause = cause
        self.tb_str = tb_str
        super().__init__(self._format())

    def _format(self) -> str:
        msg = f"task {self.function_name} failed"
        if self.tb_str:
            msg += "\n\nremote traceback:\n" + self.tb_str
        elif self.cause is not None:
            msg += f": {self.cause!r}"
        return msg

    @classmethod
    def from_exception(cls, function_name: str, exc: Exception) -> "TaskError":
        tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
        try:
            import cloudpickle

            cloudpickle.dumps(exc)
            cause: Optional[Exception] = exc
        except Exception:
            cause = None
        return cls(function_name, cause, tb)

    def __reduce__(self):
        return (TaskError, (self.function_name, self.cause, self.tb_str))


class TaskCancelledError(RayTpuError):
    pass


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ActorDiedError(RayTpuError):
    """The actor is dead (crashed, killed, or out of restarts)."""

    def __init__(self, actor_id_hex: str = "", reason: str = ""):
        self.actor_id_hex = actor_id_hex
        self.reason = reason
        super().__init__(f"actor {actor_id_hex} died: {reason}")

    def __reduce__(self):
        return (ActorDiedError, (self.actor_id_hex, self.reason))


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class ObjectLostError(RayTpuError):
    """Object data was lost and could not be reconstructed from lineage."""

    def __init__(self, object_id_hex: str = "", reason: str = ""):
        self.object_id_hex = object_id_hex
        self.reason = reason
        super().__init__(f"object {object_id_hex} lost: {reason}")

    def __reduce__(self):
        return (ObjectLostError, (self.object_id_hex, self.reason))


class ChannelClosedError(RayTpuError):
    """A compiled-graph channel was closed.

    Raised at every peer blocked on (or about to touch) the channel when
    the owning CompiledDAG is torn down or a participant actor/node dies.
    """


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupError(RayTpuError):
    pass
