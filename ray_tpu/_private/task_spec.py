"""Task specifications and scheduling strategies.

Analog of the reference's ``TaskSpecification`` (`src/ray/common/
task/task_spec.h`) and the Python scheduling-strategy surface
(`python/ray/util/scheduling_strategies.py`): a TaskSpec is the unit handed
from a submitting CoreWorker to a supervisor (for the lease) and then to the
executing worker (for the run).

Args are pre-resolved at the submitter where possible: plain values travel as
packed payloads, top-level ObjectRef args travel as (id, owner) pairs that the
executing worker fetches before invoking the function — mirroring the
reference's LocalDependencyResolver + plasma-arg split
(`transport/dependency_resolver.h`, `core_worker.cc:2852`).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.ids import ActorID, JobID, ObjectID, PlacementGroupID, TaskID

Address = Tuple[str, int]


class ArgKind(enum.Enum):
    VALUE = 0  # packed payload bytes
    REF = 1  # (ObjectID, owner Address) — fetched by the executor


@dataclasses.dataclass
class TaskArg:
    kind: ArgKind
    value: bytes | None = None
    object_id: ObjectID | None = None
    owner: Address | None = None


class TaskKind(enum.Enum):
    NORMAL = 0
    ACTOR_CREATION = 1
    ACTOR_TASK = 2


@dataclasses.dataclass
class SchedulingStrategy:
    """Base: DEFAULT = hybrid policy."""

    name: str = "DEFAULT"


@dataclasses.dataclass
class SpreadStrategy(SchedulingStrategy):
    name: str = "SPREAD"


@dataclasses.dataclass
class NodeAffinityStrategy(SchedulingStrategy):
    name: str = "NODE_AFFINITY"
    node_id_hex: str = ""
    soft: bool = False


@dataclasses.dataclass
class RandomStrategy(SchedulingStrategy):
    """Uniform choice over schedulable nodes (ref
    `policy/random_scheduling_policy.h`) — load-oblivious by design,
    for workloads that want decorrelated placement."""

    name: str = "RANDOM"


@dataclasses.dataclass
class PlacementGroupStrategy(SchedulingStrategy):
    name: str = "PLACEMENT_GROUP"
    pg_id_hex: str = ""
    bundle_index: int = -1  # -1 = any bundle


# Label operators (≈ the reference's label-selector grammar behind
# NodeLabelSchedulingStrategy, `bundle_label_selector`/
# `node_label_scheduling_policy`). Each constraint maps a label key to
# one of these; plain lists/strings shorthand to In.


@dataclasses.dataclass
class In:
    values: tuple

    def __init__(self, *values):
        self.values = tuple(values)

    def matches(self, v) -> bool:
        return v is not None and v in self.values


@dataclasses.dataclass
class NotIn:
    values: tuple

    def __init__(self, *values):
        self.values = tuple(values)

    def matches(self, v) -> bool:
        return v is None or v not in self.values


@dataclasses.dataclass
class Exists:
    def matches(self, v) -> bool:
        return v is not None


@dataclasses.dataclass
class DoesNotExist:
    def matches(self, v) -> bool:
        return v is None


def _norm_label_ops(constraints):
    out = {}
    for k, op in (constraints or {}).items():
        if isinstance(op, (list, tuple)):
            op = In(*op)
        elif isinstance(op, str):
            op = In(op)
        out[k] = op
    return out


@dataclasses.dataclass
class NodeLabelStrategy(SchedulingStrategy):
    """Schedule by node labels (≈ NodeLabelSchedulingStrategy /
    `node_label_scheduling_policy.h`): `hard` constraints filter the
    candidate set (infeasible if none match), `soft` ones order it —
    the heterogeneous-TPU-generations case (label chips by `tpu-gen`)
    the plain resource model can't express."""

    name: str = "NODE_LABEL"
    hard: dict = dataclasses.field(default_factory=dict)
    soft: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        self.hard = _norm_label_ops(self.hard)
        self.soft = _norm_label_ops(self.soft)


@dataclasses.dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    kind: TaskKind
    name: str  # human-readable, for errors/observability
    function_key: str  # controller function-table key (sha256 of pickled fn)
    args: List[TaskArg]
    # -1 = streaming generator task (`num_returns="streaming"`): the task
    # yields a dynamic number of items, each reported to the owner as it
    # is produced (≈ reference ObjectRefGenerator, _raylet.pyx:273)
    num_returns: int = 1
    # None = unspecified (defaults to 1 CPU for normal tasks); {} = explicitly
    # zero-resource (schedulable anywhere, like the reference's num_cpus=0)
    resources: Optional[Dict[str, float]] = None
    strategy: SchedulingStrategy = dataclasses.field(default_factory=SchedulingStrategy)
    max_retries: int = 0
    retry_exceptions: bool = False
    owner: Optional[Address] = None
    runtime_env: Optional[Dict[str, Any]] = None
    # actor fields
    actor_id: Optional[ActorID] = None
    method_name: str = ""
    seqno: int = -1  # per-handle sequence number for ordered actor execution
    caller_id: str = ""  # identifies the submitting handle for ordering
    max_concurrency: int = 1
    max_restarts: int = 0
    max_task_retries: int = 0
    is_async_actor: bool = False
    # distributed tracing: caller's span context (util/tracing.py); the
    # executing worker opens a child span around the user function
    trace_ctx: Optional[Dict[str, str]] = None
    # streaming only: executor pauses when this many yielded items are
    # unconsumed at the owner (0 = unbounded), ≈ the reference's
    # _generator_backpressure_num_objects
    backpressure: int = 0

    @property
    def is_streaming(self) -> bool:
        return self.num_returns < 0

    def return_ids(self) -> List[ObjectID]:
        if self.is_streaming:
            # item ids are minted per yield (ObjectID.for_task_return with
            # the yield index); the owner's stream state tracks them
            return []
        return [
            ObjectID.for_task_return(self.task_id, i) for i in range(self.num_returns)
        ]

    def required_resources(self) -> Dict[str, float]:
        if self.resources is None:
            return {"CPU": 1.0}
        return {k: v for k, v in self.resources.items() if v > 0}
