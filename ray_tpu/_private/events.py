"""Structured event framework — JSONL lifecycle events per daemon.

Analog of the reference's event framework (`src/ray/util/event.h`
RAY_EVENT macros + `dashboard/modules/event/`): daemons append one JSON
object per line to ``<session>/logs/events_<component>_<pid>.jsonl``
with a stable schema (timestamp, severity, source_type, event_type,
message, custom fields), and the state API
(`ray_tpu.util.state.list_cluster_events`) merges the session's files
into one time-ordered view. Free-text logs remain for humans; events are
the machine-queryable lifecycle record.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR")


class EventLogger:
    """Append-only JSONL writer, safe across threads; one per daemon."""

    def __init__(self, component: str, session_dir: str):
        self.component = component
        self._lock = threading.Lock()
        self._fh = None
        self.path = ""
        if session_dir:
            log_dir = os.path.join(session_dir, "logs")
            try:
                os.makedirs(log_dir, exist_ok=True)
                self.path = os.path.join(
                    log_dir, f"events_{component}_{os.getpid()}.jsonl")
                self._fh = open(self.path, "a", buffering=1)  # line-buffered
            except OSError:
                logger.warning("event log unavailable for %s", component)

    def emit(self, event_type: str, message: str = "",
             severity: str = "INFO", **fields: Any) -> None:
        if self._fh is None:
            return
        record = {
            "event_id": uuid.uuid4().hex[:16],
            "timestamp": time.time(),
            "severity": severity if severity in SEVERITIES else "INFO",
            "source_type": self.component,
            "source_pid": os.getpid(),
            "event_type": event_type,
            "message": message,
        }
        if fields:
            record["custom_fields"] = fields
        try:
            with self._lock:
                self._fh.write(json.dumps(record) + "\n")
        except Exception:
            pass  # events must never take a daemon down

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except Exception:
                pass
            self._fh = None


_null = None


def null_logger() -> EventLogger:
    """Shared no-op logger (no session dir)."""
    global _null
    if _null is None:
        _null = EventLogger("null", "")
    return _null


def read_events(session_dir: str, *, limit: int = 1000,
                event_type: Optional[str] = None,
                source_type: Optional[str] = None,
                severity: Optional[str] = None) -> List[Dict[str, Any]]:
    """Merge every daemon's event file in *session_dir* into one
    time-ordered list (newest last), with optional filters."""
    log_dir = os.path.join(session_dir, "logs")
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(log_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("events_") and name.endswith(".jsonl")):
            continue
        try:
            with open(os.path.join(log_dir, name)) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if event_type and rec.get("event_type") != event_type:
                        continue
                    if source_type and rec.get("source_type") != source_type:
                        continue
                    if severity and rec.get("severity") != severity:
                        continue
                    out.append(rec)
        except OSError:
            continue
    out.sort(key=lambda r: r.get("timestamp", 0))
    return out[-limit:]
