"""Internal KV client — thin wrappers over the controller's KV service.

Analog of the reference's `ray.experimental.internal_kv` (backed by the GCS
internal KV, `src/ray/gcs/gcs_server/gcs_kv_manager.h`): a namespaced
key→value store used by libraries (collective group metadata, serve config,
job table) rather than by user code.
"""

from __future__ import annotations

from typing import Any, List, Optional


def _core():
    from ray_tpu._private.api import _require_core

    return _require_core()


def kv_put(key: str, value: Any, *, ns: str = "default", overwrite: bool = True) -> bool:
    core = _core()
    from ray_tpu._private.serialization import payload_nbytes

    size = payload_nbytes(value)
    if size > core.config.kv_max_value_bytes:
        # fail before serializing a tensor-sized frame onto the control
        # plane (the controller enforces the same cap authoritatively)
        raise ValueError(
            f"kv_put value for {key!r} is {size} bytes, above the "
            f"control-plane cap of {core.config.kv_max_value_bytes} "
            f"(RAY_TPU_KV_MAX_VALUE_BYTES). Move tensor-sized payloads "
            f"through the object store (ray_tpu.put) or the collective "
            f"data plane (ray_tpu.util.collective), not the controller KV.")
    return core._run(
        core.clients.get(core.controller_addr).call(
            "kv_put", {"ns": ns, "key": key, "value": value, "overwrite": overwrite}
        )
    )


def kv_get(key: str, *, ns: str = "default") -> Optional[Any]:
    core = _core()
    return core._run(
        core.clients.get(core.controller_addr).call("kv_get", {"ns": ns, "key": key})
    )


def kv_exists(key: str, *, ns: str = "default") -> bool:
    core = _core()
    return core._run(
        core.clients.get(core.controller_addr).call("kv_exists", {"ns": ns, "key": key})
    )


def kv_del(key: str, *, ns: str = "default") -> bool:
    core = _core()
    return core._run(
        core.clients.get(core.controller_addr).call("kv_del", {"ns": ns, "key": key})
    )


def kv_wait(key: str, timeout: float = 30.0, *, ns: str = "default") -> Any:
    """Long-poll for ``key``: returns its value as soon as it exists
    (possibly immediately), raises TimeoutError after ``timeout`` seconds.
    ONE parked RPC per ~30 s slice replaces client-side sleep-and-repoll
    loops on the control plane (collective rendezvous, PG readiness).

    Controller-restart safe: a wait parked on a controller that is then
    killed fails with the severed connection — the client RE-ISSUES the
    wait under the SAME deadline budget instead of hanging or surfacing a
    spurious error. A put that landed in the controller's WAL before the
    kill resolves the re-issued wait immediately from the recovered KV
    (the server-side found-fast path); a put after recovery resolves it
    through ``_kv_notify`` as usual."""
    import time

    from ray_tpu._private.rpc import RpcConnectionError, RpcTimeoutError

    core = _core()
    deadline = time.monotonic() + timeout
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise TimeoutError(
                f"kv_wait: key {key!r} (ns={ns!r}) did not appear within "
                f"{timeout}s")
        slice_s = min(remaining, 30.0)
        try:
            reply = core._run(
                core.clients.get(core.controller_addr).call(
                    "kv_wait", {"ns": ns, "key": key, "timeout": slice_s},
                    timeout=slice_s + core.config.rpc_request_timeout_s,
                )
            )
        except (RpcConnectionError, RpcTimeoutError):
            if deadline - time.monotonic() <= 0.2:
                raise TimeoutError(
                    f"kv_wait: key {key!r} (ns={ns!r}) did not appear "
                    f"within {timeout}s (controller unreachable at the "
                    f"deadline)") from None
            # controller died mid-park: re-arm after a beat (the re-issued
            # call's connect path patiently waits out the restart window)
            time.sleep(0.2)
            continue
        if reply.get("found"):
            return reply["value"]


def kv_keys(prefix: str = "", *, ns: str = "default") -> List[str]:
    core = _core()
    return core._run(
        core.clients.get(core.controller_addr).call("kv_keys", {"ns": ns, "prefix": prefix})
    )
