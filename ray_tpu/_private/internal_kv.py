"""Internal KV client — thin wrappers over the controller's KV service.

Analog of the reference's `ray.experimental.internal_kv` (backed by the GCS
internal KV, `src/ray/gcs/gcs_server/gcs_kv_manager.h`): a namespaced
key→value store used by libraries (collective group metadata, serve config,
job table) rather than by user code.
"""

from __future__ import annotations

from typing import Any, List, Optional


def _core():
    from ray_tpu._private.api import _require_core

    return _require_core()


def kv_put(key: str, value: Any, *, ns: str = "default", overwrite: bool = True) -> bool:
    core = _core()
    return core._run(
        core.clients.get(core.controller_addr).call(
            "kv_put", {"ns": ns, "key": key, "value": value, "overwrite": overwrite}
        )
    )


def kv_get(key: str, *, ns: str = "default") -> Optional[Any]:
    core = _core()
    return core._run(
        core.clients.get(core.controller_addr).call("kv_get", {"ns": ns, "key": key})
    )


def kv_exists(key: str, *, ns: str = "default") -> bool:
    core = _core()
    return core._run(
        core.clients.get(core.controller_addr).call("kv_exists", {"ns": ns, "key": key})
    )


def kv_del(key: str, *, ns: str = "default") -> bool:
    core = _core()
    return core._run(
        core.clients.get(core.controller_addr).call("kv_del", {"ns": ns, "key": key})
    )


def kv_keys(prefix: str = "", *, ns: str = "default") -> List[str]:
    core = _core()
    return core._run(
        core.clients.get(core.controller_addr).call("kv_keys", {"ns": ns, "prefix": prefix})
    )
