"""Small asyncio bridges shared across the runtime."""

from __future__ import annotations

import asyncio
from typing import Any, Callable

END_OF_ITERATION = object()
"""Sentinel returned by :func:`step_off_loop` at iterator exhaustion —
StopIteration itself can neither escape a coroutine (PEP 479) nor be
raised into a Future."""


async def step_off_loop(step: Callable[[], Any], ctx=None) -> Any:
    """Run one step of a sync iterator in the default executor (so the
    event loop keeps serving) and return its value, or END_OF_ITERATION
    when the iterator is exhausted. ``ctx`` (a contextvars.Context) runs
    the step under the caller's request context when given."""

    def run():
        try:
            return ctx.run(step) if ctx is not None else step()
        except StopIteration:
            return END_OF_ITERATION

    return await asyncio.get_running_loop().run_in_executor(None, run)
