"""Public user API: init / remote / get / put / wait / actors.

Analog of the reference's Ray Core Python surface
(`python/ray/_private/worker.py:1214,2537,2655,2720,3113`,
`python/ray/remote_function.py:266`, `python/ray/actor.py:854,1364`).
"""

from __future__ import annotations

import functools
import hashlib
import inspect
import logging
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ray_tpu._private import serialization
from ray_tpu._private.config import Config
from ray_tpu._private.core_worker import CoreWorker
from ray_tpu._private.ids import ActorID, JobID, ObjectID
from ray_tpu._private.task_spec import (
    NodeAffinityStrategy,
    PlacementGroupStrategy,
    SchedulingStrategy,
    SpreadStrategy,
)

logger = logging.getLogger(__name__)

_global_lock = threading.RLock()
_core: Optional[CoreWorker] = None
_node_handle = None  # local cluster bootstrap (driver-started head)
_namespace = "default"


# --------------------------------------------------------------------- refs


class ObjectRef:
    """A future for a task return or put object (≈ ray.ObjectRef)."""

    __slots__ = ("_object_id", "_owner_addr", "_skip_rc", "__weakref__")

    def __init__(
        self,
        object_id: ObjectID,
        owner_addr: Tuple[str, int],
        skip_ref_counting: bool = False,
    ):
        self._object_id = object_id
        self._owner_addr = tuple(owner_addr)
        self._skip_rc = skip_ref_counting
        if not skip_ref_counting and _core is not None:
            _core.add_local_ref(object_id, self._owner_addr)

    def hex(self) -> str:
        return self._object_id.hex()

    def __repr__(self) -> str:
        return f"ObjectRef({self._object_id.hex()[:16]})"

    def __eq__(self, other) -> bool:
        return isinstance(other, ObjectRef) and other._object_id == self._object_id

    def __hash__(self) -> int:
        return hash(self._object_id)

    def __del__(self):
        if not self._skip_rc and _core is not None:
            try:
                _core.remove_local_ref(self._object_id, self._owner_addr)
            except Exception:
                pass

    def __reduce__(self):
        return (_deserialize_ref, (self._object_id.binary(), self._owner_addr))

    def __await__(self):
        """Awaitable inside async actors / asyncio code (ray parity:
        ObjectRefs are awaitable)."""
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()

    def future(self):
        """concurrent.futures.Future resolving to the value.

        Driven by the core worker's own event loop (no per-ref helper
        thread: N awaited refs cost zero extra threads, and cancelling the
        future cancels the underlying coroutine instead of stranding a
        blocked thread)."""
        import asyncio

        core = _require_core()
        return asyncio.run_coroutine_threadsafe(
            core._async_get_one(self._object_id, self._owner_addr, None),
            core.loop,
        )


class ObjectRefGenerator:
    """Iterator over a streaming generator task's yielded items
    (≈ ray.ObjectRefGenerator, `python/ray/_raylet.pyx:273`). Each
    ``next()`` blocks until the executor reports the next item and yields
    an ordinary ObjectRef (pass it to get/wait/tasks as usual). Iteration
    raises the task's error after the last successfully yielded item, and
    StopIteration at exhaustion. Usable from async code via ``async for``.

    Not serializable: the stream state lives in the owner process (the
    reference has the same restriction for the plain generator type)."""

    def __init__(self, task_id, owner_addr):
        self._task_id = task_id
        self._owner_addr = tuple(owner_addr)
        self._cursor = 0
        self._released = False

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        return self._next(timeout=None)

    def _next(self, timeout: Optional[float] = None) -> ObjectRef:
        core = _require_core()
        oid = core.stream_next(self._task_id, self._cursor, timeout)
        self._cursor += 1
        return ObjectRef(oid, self._owner_addr)

    next = _next  # explicit-timeout spelling: gen.next(timeout=...)

    def __aiter__(self):
        return self

    async def __anext__(self) -> ObjectRef:
        from ray_tpu._private.async_utils import END_OF_ITERATION, step_off_loop

        out = await step_off_loop(self.__next__)
        if out is END_OF_ITERATION:
            raise StopAsyncIteration
        return out

    def completed(self) -> bool:
        core = _require_core()
        stream = core._streams.get(self._task_id)
        return stream is None or (stream.finished
                                  and self._cursor >= len(stream.items))

    def task_id(self):
        return self._task_id

    def __reduce__(self):
        raise TypeError("ObjectRefGenerator is not serializable; consume it "
                        "in the owner process and pass the yielded "
                        "ObjectRefs instead")

    def __del__(self):
        if not self._released and _core is not None:
            try:
                _core.stream_released(self._task_id)
            except Exception:
                pass
            self._released = True


def _deserialize_ref(raw: bytes, owner) -> ObjectRef:
    ref = ObjectRef(ObjectID(raw), tuple(owner))
    # register as borrower with the owner (best-effort distributed refcount)
    if _core is not None and tuple(owner) != tuple(_core.address or ()):
        try:
            import asyncio

            asyncio.run_coroutine_threadsafe(
                _core.clients.get(tuple(owner)).notify(
                    "add_borrow", {"object_id": raw}
                ),
                _core.loop,
            )
        except Exception:
            pass
    return ref


# --------------------------------------------------------------------- init


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[Dict[str, float]] = None,
    object_store_memory: Optional[int] = None,
    namespace: str = "default",
    log_to_driver: bool = True,
    _system_config: Optional[Dict[str, Any]] = None,
    ignore_reinit_error: bool = False,
) -> Dict[str, Any]:
    """Connect to (or start) a cluster. ≈ ray.init (worker.py:1214)."""
    global _core, _node_handle, _namespace
    with _global_lock:
        if _core is not None:
            if ignore_reinit_error:
                return {"address": f"{_core.controller_addr[0]}:{_core.controller_addr[1]}"}
            raise RuntimeError("ray_tpu.init() called twice; use shutdown() first")
        if _client is not None:
            if ignore_reinit_error:
                return {"address": _client._address, "client": True}
            raise RuntimeError("ray_tpu.init() called twice; use shutdown() first")
        if address and address.startswith("client://"):
            from ray_tpu.util import client as _client_mod

            _namespace = namespace
            ctx = _client_mod.connect(address[len("client://"):],
                                      namespace=namespace)
            return {"address": address, "client": True,
                    "namespace": ctx._server_namespace}
        config = Config.from_env(_system_config)
        if object_store_memory:
            config.object_store_memory_bytes = object_store_memory
        _namespace = namespace

        if address in (None, "local"):
            from ray_tpu._private.node import NodeHandle

            _node_handle = NodeHandle.start_head(
                config,
                num_cpus=num_cpus,
                num_tpus=num_tpus,
                resources=resources,
            )
            controller_addr = _node_handle.controller_addr
            supervisor_addr = _node_handle.supervisor_addr
        else:
            if address == "auto":
                address = os.environ.get("RAY_TPU_ADDRESS", "")
                if not address:
                    raise ConnectionError("address='auto' but RAY_TPU_ADDRESS unset")
            host, port = address.rsplit(":", 1)
            controller_addr = (host, int(port))
            supervisor_addr = _find_local_supervisor(config, controller_addr)

        core = CoreWorker(
            config,
            controller_addr,
            supervisor_addr,
            _new_job_id(controller_addr),
            role="driver",
        )
        core.start()
        _core = core
        core._run(
            core.clients.get(controller_addr).call(
                "job_register",
                {"job_id_hex": core.job_id.hex(), "driver_address": core.address},
            )
        )
        if log_to_driver:
            # worker stdout/stderr stream to this process (supervisors
            # tail the files and publish; ≈ the reference's log monitor)
            my_job_hex = core.job_id.hex()

            def _print_worker_logs(msg):
                import sys as _sys

                # only THIS driver's workers (messages carry the job that
                # spawned the worker; untagged = pre-tagging pooled worker)
                job = msg.get("job_id_hex", "")
                if job and job != my_job_hex:
                    return
                stream = (_sys.stderr if msg.get("stream") == "stderr"
                          else _sys.stdout)
                tag = f"({msg.get('node', '?')} pid={msg.get('pid', '?')})"
                for line in msg.get("lines", []):
                    print(f"{tag} {line}", file=stream)

            core.subscribe("worker_logs", _print_worker_logs)
        session_dir = getattr(_node_handle, "session_dir", "")
        if session_dir:
            os.environ["RAY_TPU_SESSION_DIR"] = session_dir
        return {
            "address": f"{controller_addr[0]}:{controller_addr[1]}",
            "node_id": core.node_id_hex,
            "session_dir": session_dir,
        }


def _new_job_id(controller_addr) -> JobID:
    """Controller-issued job number (cluster-unique across drivers)."""
    import asyncio

    from ray_tpu._private.config import global_config
    from ray_tpu._private.rpc import RpcClient, retry_call

    async def ask():
        client = RpcClient(controller_addr)
        try:
            # job_new is replay-cached server-side, so retrying across a
            # controller hiccup can never mint two numbers for this driver
            return await retry_call(
                client, "job_new", timeout=30, per_call_timeout=10,
                base_interval_s=global_config().rpc_retry_interval_ms / 1000.0)
        finally:
            await client.close()

    return JobID.from_int(asyncio.run(ask()))


def _find_local_supervisor(config, controller_addr):
    import asyncio

    from ray_tpu._private.rpc import RpcClient

    async def find():
        client = RpcClient(controller_addr)
        try:
            views = await client.call("node_views")
        finally:
            await client.close()
        alive = [v for v in views if v["alive"]]
        if not alive:
            return None
        # prefer a supervisor on this host
        import socket

        local_names = {"127.0.0.1", "localhost", socket.gethostname()}
        try:
            local_names.add(socket.gethostbyname(socket.gethostname()))
        except OSError:
            pass
        for v in alive:
            if v["address"][0] in local_names:
                return tuple(v["address"])
        return tuple(alive[0]["address"])

    return asyncio.run(find())


def _connect_existing(core: CoreWorker) -> None:
    """Install an already-started CoreWorker as this process's runtime
    (used by worker processes)."""
    global _core
    _core = core


# ------------------------------------------------------------------ client mode
# ≈ ray.util.client: when connected through a client server, the module-level
# API proxies through a ClientContext instead of a local CoreWorker.

_client = None


def _install_client(ctx) -> None:
    global _client
    if _core is not None:
        raise RuntimeError(
            "cannot enter client mode: this process already runs a driver "
            "(call shutdown() first)")
    _client = ctx


def _uninstall_client() -> None:
    global _client
    if _client is not None:
        _client.disconnect()
        _client = None


def shutdown() -> None:
    global _core, _node_handle
    with _global_lock:
        _uninstall_client()
        if _node_handle is not None:
            # local usage report (usage.py; collector POST is opt-in)
            try:
                from ray_tpu._private import usage

                usage.write_report(_node_handle.session_dir)
            except Exception:
                pass
        if _core is not None:
            try:
                _core._run(
                    _core.clients.get(_core.controller_addr).call(
                        "job_finish", {"job_id_hex": _core.job_id.hex()}, timeout=2
                    ),
                    timeout=3,
                )
            except Exception:
                pass
            _core.shutdown()
            _core = None
        if _node_handle is not None:
            _node_handle.stop()
            _node_handle = None


def is_initialized() -> bool:
    return _core is not None or _client is not None


def _require_core() -> CoreWorker:
    if _core is None:
        init()
    return _core


# --------------------------------------------------------------------- core ops


def put(value: Any) -> ObjectRef:
    if _client is not None:
        return _client.put(value)
    core = _require_core()
    oid, owner = core.put(value)
    return ObjectRef(oid, owner)


def get(
    refs: Union[ObjectRef, Sequence[ObjectRef]], *, timeout: Optional[float] = None
) -> Any:
    if getattr(refs, "_is_compiled_dag_ref", False):
        # compiled-graph step: resolves by reading the output channel(s)
        # directly — no object layer, no RPCs (ray.get parity for
        # CompiledDAGRef)
        return refs.get(timeout=timeout)
    if _client is not None:
        return _client.get(refs, timeout=timeout)
    core = _require_core()
    single = isinstance(refs, ObjectRef)
    batch = [refs] if single else list(refs)
    for r in batch:
        if not isinstance(r, ObjectRef) and \
                not getattr(r, "_is_compiled_dag_ref", False):
            raise TypeError(f"get() expects ObjectRef(s), got {type(r).__name__}")
    if not single and any(
            getattr(r, "_is_compiled_dag_ref", False) for r in batch):
        # a list mixing compiled-graph steps with ordinary refs: batch
        # the ObjectRefs through the object layer, read the compiled
        # steps from their channels, preserve order. One deadline covers
        # every resolve — not timeout-per-item
        import time as _time

        deadline = None if timeout is None \
            else _time.monotonic() + timeout

        def remaining() -> Optional[float]:
            return None if deadline is None \
                else max(0.001, deadline - _time.monotonic())

        obj_idx = [i for i, r in enumerate(batch)
                   if isinstance(r, ObjectRef)]
        obj_vals = core.get([batch[i] for i in obj_idx],
                            timeout=remaining()) if obj_idx else []
        out: list = [None] * len(batch)
        for i, v in zip(obj_idx, obj_vals):
            out[i] = v
        for i, r in enumerate(batch):
            if not isinstance(r, ObjectRef):
                out[i] = r.get(timeout=remaining())
        return out
    values = core.get(batch, timeout=timeout)
    return values[0] if single else values


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds the number of refs")
    if _client is not None:
        return _client.wait(refs, num_returns=num_returns, timeout=timeout)
    core = _require_core()
    return core.wait(list(refs), num_returns=num_returns, timeout=timeout)


def kill(actor: "ActorHandle", *, no_restart: bool = True) -> None:
    if _client is not None:
        _client.kill(actor, no_restart=no_restart)
        return
    _require_core().kill_actor(actor._actor_id, no_restart=no_restart)


def cancel(ref, *, force: bool = False) -> None:
    """Best-effort cancellation of a queued task (ObjectRef or
    ObjectRefGenerator — cancelling a generator stops the stream between
    yields; items already yielded stay consumable)."""
    if _client is not None:
        _client.cancel(ref, force=force)
        return
    core = _require_core()
    if isinstance(ref, ObjectRefGenerator):
        task_id = ref._task_id
    else:
        task_id = ref._object_id.task_id()
    task = core._inflight_tasks.get(task_id)
    if task is None:
        return
    addr = None
    if task.lease is not None:
        addr = task.lease.worker_addr
    elif task.spec.actor_id is not None:
        # actor tasks ride the handle's push channel, not a lease
        state = core._actor_states.get(task.spec.actor_id.hex())
        addr = state.address if state is not None else None
    if addr is not None:
        import asyncio

        asyncio.run_coroutine_threadsafe(
            core.clients.get(tuple(addr)).call(
                "cancel", {"task_id": task_id.binary()}
            ),
            core.loop,
        )


def nodes() -> List[Dict[str, Any]]:
    if _client is not None:
        return _client.nodes()
    core = _require_core()
    return core._run(core.clients.get(core.controller_addr).call("node_views"))


def cluster_resources() -> Dict[str, float]:
    if _client is not None:
        return _client.cluster_resources()
    core = _require_core()
    status = core._run(core.clients.get(core.controller_addr).call("cluster_status"))
    return status["total_resources"]


def available_resources() -> Dict[str, float]:
    if _client is not None:
        return _client.available_resources()
    core = _require_core()
    status = core._run(core.clients.get(core.controller_addr).call("cluster_status"))
    return status["available_resources"]


class RuntimeContext:
    def __init__(self, core: CoreWorker):
        self._core = core

    @property
    def job_id(self) -> str:
        return self._core.job_id.hex()

    @property
    def node_id(self) -> str:
        return self._core.node_id_hex

    @property
    def worker_id(self) -> str:
        return self._core.worker_id.hex()

    @property
    def actor_id(self) -> Optional[str]:
        return self._core.actor_id.hex() if self._core.actor_id else None

    def get_tpu_chips(self) -> List[int]:
        raw = os.environ.get("TPU_VISIBLE_CHIPS", "")
        return [int(c) for c in raw.split(",") if c.strip()]

    # getter-style aliases matching the reference's RuntimeContext
    # (`python/ray/runtime_context.py` get_node_id/get_job_id/...)
    def get_node_id(self) -> str:
        return self.node_id

    def get_job_id(self) -> str:
        return self.job_id

    def get_worker_id(self) -> str:
        return self.worker_id

    def get_actor_id(self) -> Optional[str]:
        return self.actor_id


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(_require_core())


# --------------------------------------------------------------------- remote


class RemoteFunction:
    """≈ ray.remote_function.RemoteFunction (remote_function.py:40)."""

    def __init__(self, fn, options: Dict[str, Any]):
        self._fn = fn
        self._options = options
        self._blob: Optional[bytes] = None
        self._key: Optional[str] = None
        functools.update_wrapper(self, fn)

    def _materialize(self):
        if self._key is None:
            self._blob = serialization.dumps(self._fn)
            self._key = hashlib.sha256(self._blob).hexdigest()
        return self._key, self._blob

    def options(self, **overrides) -> "RemoteFunction":
        new = dict(self._options)
        new.update(overrides)
        rf = RemoteFunction(self._fn, new)
        rf._key, rf._blob = self._key, self._blob
        return rf

    def remote(self, *args, **kwargs):
        if _client is not None:
            key, blob = self._materialize()
            return _client.submit_task(
                blob, self._fn.__qualname__, args, kwargs, self._options)
        core = _require_core()
        opts = self._options
        key, blob = self._materialize()
        resources = _resources_from_options(opts)
        num_returns = _norm_num_returns(opts.get("num_returns", 1))
        out = core.submit_task(
            None,
            args,
            kwargs,
            name=opts.get("name") or self._fn.__qualname__,
            num_returns=num_returns,
            resources=resources,
            strategy=_strategy_from_options(opts),
            max_retries=opts.get("max_retries", -1),
            retry_exceptions=bool(opts.get("retry_exceptions", False)),
            runtime_env=_resolve_runtime_env(opts.get("runtime_env"), core),
            function_key=key,
            function_blob=blob,
            backpressure=_backpressure_from_options(opts),
        )
        if num_returns < 0:
            return ObjectRefGenerator(out, core.address)
        refs = [ObjectRef(oid, core.address) for oid in out]
        return refs[0] if num_returns == 1 else refs

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node instead of submitting (ray.dag analog)."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._fn.__name__}() cannot be called directly; "
            f"use .remote()"
        )


def _resolve_runtime_env(env, core):
    """Package local working_dir/py_modules paths into content-addressed
    URIs uploaded to the cluster KV (see _private/runtime_env.py)."""
    if not env:
        return env
    from ray_tpu._private.runtime_env import resolve_runtime_env

    return resolve_runtime_env(env, core)


def _norm_num_returns(v) -> int:
    """"streaming"/"dynamic" -> -1 (generator task); ints pass through."""
    if v in ("streaming", "dynamic"):
        return -1
    return int(v)


def _backpressure_from_options(opts: Dict[str, Any]) -> int:
    """Generator backpressure window; accepts our name and the
    reference's `_generator_backpressure_num_objects`."""
    v = opts.get("generator_backpressure",
                 opts.get("_generator_backpressure_num_objects", 0))
    return max(0, int(v or 0))


def _resources_from_options(opts: Dict[str, Any]) -> Optional[Dict[str, float]]:
    """None = unspecified (framework default); explicit zeros are preserved."""
    specified = False
    resources: Dict[str, float] = {}
    if opts.get("resources") is not None:
        resources.update({k: float(v) for k, v in opts["resources"].items()})
        specified = True
    if opts.get("num_cpus") is not None:
        resources["CPU"] = float(opts["num_cpus"])
        specified = True
    if opts.get("num_tpus") is not None:
        resources["TPU"] = float(opts["num_tpus"])
        specified = True
    if opts.get("memory") is not None:
        resources["memory"] = float(opts["memory"])
        specified = True
    return resources if specified else None


def _strategy_from_options(opts: Dict[str, Any]) -> SchedulingStrategy:
    strat = opts.get("scheduling_strategy")
    if isinstance(strat, SchedulingStrategy):
        return strat
    if strat == "SPREAD":
        return SpreadStrategy()
    if strat == "RANDOM":
        from ray_tpu._private.task_spec import RandomStrategy

        return RandomStrategy()
    if isinstance(strat, str) and strat not in ("DEFAULT", ""):
        raise ValueError(
            f"unknown scheduling_strategy string {strat!r}; use 'SPREAD', "
            "'RANDOM', 'DEFAULT', or a strategy object from "
            "ray_tpu.util.scheduling_strategies")
    pg = opts.get("placement_group")
    if pg is not None:
        return PlacementGroupStrategy(
            pg_id_hex=pg.id.hex(),
            bundle_index=opts.get("placement_group_bundle_index", -1),
        )
    return SchedulingStrategy()


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns=1,
                 backpressure: int = 0):
        self._handle = handle
        self._name = name
        self._num_returns = _norm_num_returns(num_returns)
        self._backpressure = backpressure

    def options(self, num_returns=None, **kw) -> "ActorMethod":
        # unspecified fields inherit the current values so chained
        # .options(num_returns="streaming").options(backpressure=2)
        # composes (advisor r4; mirrors DeploymentHandle.options)
        return ActorMethod(
            self._handle, self._name,
            self._num_returns if num_returns is None else num_returns,
            backpressure=(_backpressure_from_options(kw)
                          if ("generator_backpressure" in kw or
                              "_generator_backpressure_num_objects" in kw)
                          else self._backpressure))

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node for this actor method (ray.dag analog)."""
        from ray_tpu.dag import ClassMethodNode

        return ClassMethodNode(self, args, kwargs)

    def remote(self, *args, **kwargs):
        core = _require_core()
        out = core.submit_actor_task(
            self._handle._actor_id,
            self._name,
            args,
            kwargs,
            num_returns=self._num_returns,
            max_task_retries=self._handle._max_task_retries,
            backpressure=self._backpressure,
        )
        if self._num_returns < 0:
            return ObjectRefGenerator(out, core.address)
        refs = [ObjectRef(oid, core.address) for oid in out]
        return refs[0] if self._num_returns == 1 else refs

    def __call__(self, *a, **k):
        raise TypeError(f"actor method {self._name}() must be invoked via .remote()")


class ActorHandle:
    """≈ ray.actor.ActorHandle (actor.py:1226)."""

    def __init__(self, actor_id: ActorID, max_task_retries: int = 0, class_name: str = ""):
        self._actor_id = actor_id
        self._max_task_retries = max_task_retries
        self._class_name = class_name

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self) -> str:
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (
            ActorHandle,
            (self._actor_id, self._max_task_retries, self._class_name),
        )


class ActorClass:
    """≈ ray.actor.ActorClass (actor.py:566)."""

    def __init__(self, cls, options: Dict[str, Any]):
        self._cls = cls
        self._options = options

    def options(self, **overrides) -> "ActorClass":
        new = dict(self._options)
        new.update(overrides)
        return ActorClass(self._cls, new)

    def remote(self, *args, **kwargs) -> ActorHandle:
        if _client is not None:
            return _client.create_actor(self._cls, args, kwargs, self._options)
        core = _require_core()
        opts = self._options
        resources = _resources_from_options(opts)
        is_async = any(
            inspect.iscoroutinefunction(m) or inspect.isasyncgenfunction(m)
            for _, m in inspect.getmembers(self._cls, inspect.isfunction)
        )
        actor_id, _ = core.create_actor(
            self._cls,
            args,
            kwargs,
            name=opts.get("name", ""),
            namespace=opts.get("namespace", _namespace),
            resources=resources,
            strategy=_strategy_from_options(opts),
            max_restarts=opts.get("max_restarts", 0),
            max_task_retries=opts.get("max_task_retries", 0),
            max_concurrency=opts.get("max_concurrency", 1),
            is_async=is_async,
            runtime_env=_resolve_runtime_env(opts.get("runtime_env"), core),
            detached=opts.get("lifetime") == "detached",
            class_name=self._cls.__name__,
        )
        return ActorHandle(
            actor_id,
            max_task_retries=opts.get("max_task_retries", 0),
            class_name=self._cls.__name__,
        )

    def __call__(self, *a, **k):
        raise TypeError(
            f"actor class {self._cls.__name__} must be instantiated via .remote()"
        )


def remote(*args, **kwargs):
    """``@remote`` / ``@remote(num_cpus=..., num_tpus=..., ...)``
    ≈ ray.remote (worker.py:3113)."""

    def decorate(target):
        if inspect.isclass(target):
            return ActorClass(target, kwargs)
        return RemoteFunction(target, kwargs)

    if len(args) == 1 and callable(args[0]) and not kwargs:
        return decorate(args[0])
    if args:
        raise TypeError("@remote takes keyword options only")
    return decorate


def method(**opts):
    """Per-method options decorator (num_returns), ≈ ray.method."""

    def wrap(fn):
        fn._method_options = opts
        return fn

    return wrap


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    if _client is not None:
        return _client.get_actor(name, namespace)
    core = _require_core()
    rec = core._run(
        core.clients.get(core.controller_addr).call(
            "actor_by_name",
            {"name": name, "namespace": namespace or _namespace},
        )
    )
    if rec is None or rec["state"] == "DEAD":
        raise ValueError(f"actor {name!r} not found in namespace {namespace or _namespace!r}")
    return ActorHandle(
        ActorID.from_hex(rec["actor_id_hex"]), class_name=rec.get("class_name", "")
    )
