"""Process-local metrics registry with Prometheus text exposition.

Analog of the reference's stats layer (`src/ray/stats/metric.h:392`
Counter/Gauge/Histogram + `metric_defs.h:46` definitions) and the
Prometheus export path (`python/ray/_private/metrics_agent.py`), without
OpenCensus: a lock-protected registry per process, rendered on demand in
Prometheus text format, served by each daemon's HTTP endpoint
(`http_util.py` in this package).

User-facing wrappers live in `ray_tpu.util.metrics`.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Dict[str, str]]) -> _LabelKey:
    return tuple(sorted((labels or {}).items()))


def _escape_label_value(v: str) -> str:
    """Prometheus exposition escaping: one bad user label value must not
    invalidate the whole scrape."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _render_labels(key: _LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)
    return "{" + inner + "}"


class Metric:
    def __init__(self, name: str, description: str, registry: "Registry"):
        self.name = name
        self.description = description
        self._lock = threading.Lock()
        registry._register(self)

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        """Current value for one label set (counter-based assertions in
        tests; 0.0 when the series was never set/incremented). Only
        meaningful for single-valued metrics (Counter/Gauge)."""
        with self._lock:
            return getattr(self, "_values", {}).get(_label_key(labels), 0.0)

    def total(self) -> float:
        """Sum over every label set (Counter/Gauge): the
        "did ANY series move" form counter-based tests need — e.g. the
        compiled-graph suite proves a steady-state step issues zero
        control RPCs by snapshotting the rpc client-call counter's total
        across all method labels."""
        with self._lock:
            return float(sum(getattr(self, "_values", {}).values()))


class Counter(Metric):
    TYPE = "counter"

    def __init__(self, name, description="", registry=None):
        super().__init__(name, description, registry or default_registry())
        self._values: Dict[_LabelKey, float] = {}

    def inc(self, value: float = 1.0,
            labels: Optional[Dict[str, str]] = None) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def render(self) -> List[str]:
        with self._lock:
            return [
                f"{self.name}{_render_labels(k)} {v}"
                for k, v in sorted(self._values.items())
            ]


class Gauge(Metric):
    TYPE = "gauge"

    def __init__(self, name, description="", registry=None):
        super().__init__(name, description, registry or default_registry())
        self._values: Dict[_LabelKey, float] = {}

    def set(self, value: float,
            labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, value: float = 1.0, labels=None) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def dec(self, value: float = 1.0, labels=None) -> None:
        self.inc(-value, labels)

    def render(self) -> List[str]:
        with self._lock:
            return [
                f"{self.name}{_render_labels(k)} {v}"
                for k, v in sorted(self._values.items())
            ]


DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   60.0, 300.0)


class _HistogramTimer:
    """Context manager recording a wall-clock span into a Histogram."""

    def __init__(self, hist: "Histogram", labels: Optional[Dict[str, str]]):
        self._hist = hist
        self._labels = labels
        self._t0 = 0.0

    def __enter__(self) -> "_HistogramTimer":
        import time

        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        import time

        self._hist.observe(time.perf_counter() - self._t0, self._labels)


class Histogram(Metric):
    TYPE = "histogram"

    def __init__(self, name, description="",
                 buckets: Sequence[float] = DEFAULT_BUCKETS, registry=None):
        super().__init__(name, description, registry or default_registry())
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[_LabelKey, List[int]] = {}
        self._sums: Dict[_LabelKey, float] = {}
        self._totals: Dict[_LabelKey, int] = {}

    def observe(self, value: float,
                labels: Optional[Dict[str, str]] = None) -> None:
        key = _label_key(labels)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            if idx < len(counts):
                counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def time(self, labels: Optional[Dict[str, str]] = None) -> _HistogramTimer:
        """``with hist.time():`` — observe the block's wall-clock seconds
        (the collective round / RPC latency idiom)."""
        return _HistogramTimer(self, labels)

    def count_total(self) -> int:
        """Observations across every label set — the "did this span get
        recorded at all" form counter-based tests need (e.g. proving
        CollectiveWork.wait() instrumented its block)."""
        with self._lock:
            return int(sum(self._totals.values()))

    def sum_total(self) -> float:
        """Sum of observed values across every label set (overlap-fraction
        arithmetic: wait_seconds.sum_total() / round_seconds.sum_total())."""
        with self._lock:
            return float(sum(self._sums.values()))

    def render(self) -> List[str]:
        out: List[str] = []
        with self._lock:
            for key, counts in sorted(self._counts.items()):
                cumulative = 0
                for bound, c in zip(self.buckets, counts):
                    cumulative += c
                    lk = dict(key)
                    lk["le"] = repr(bound)
                    out.append(
                        f"{self.name}_bucket{_render_labels(_label_key(lk))}"
                        f" {cumulative}")
                lk = dict(key)
                lk["le"] = "+Inf"
                out.append(
                    f"{self.name}_bucket{_render_labels(_label_key(lk))}"
                    f" {self._totals[key]}")
                out.append(
                    f"{self.name}_sum{_render_labels(key)} {self._sums[key]}")
                out.append(
                    f"{self.name}_count{_render_labels(key)} "
                    f"{self._totals[key]}")
        return out


class Registry:
    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def _register(self, metric: Metric) -> None:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None and type(existing) is not type(metric):
                raise ValueError(
                    f"metric {metric.name!r} already registered with a "
                    f"different type")
            self._metrics[metric.name] = metric

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def render_prometheus(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            lines.append(f"# HELP {m.name} {m.description}")
            lines.append(f"# TYPE {m.name} {m.TYPE}")
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


_default: Optional[Registry] = None
_default_lock = threading.Lock()


def default_registry() -> Registry:
    global _default
    with _default_lock:
        if _default is None:
            _default = Registry()
        return _default
