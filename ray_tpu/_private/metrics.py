"""Process-local metrics registry with Prometheus text exposition.

Analog of the reference's stats layer (`src/ray/stats/metric.h:392`
Counter/Gauge/Histogram + `metric_defs.h:46` definitions) and the
Prometheus export path (`python/ray/_private/metrics_agent.py`), without
OpenCensus: a lock-protected registry per process, rendered on demand in
Prometheus text format, served by each daemon's HTTP endpoint
(`http_util.py` in this package).

User-facing wrappers live in `ray_tpu.util.metrics`.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Dict[str, str]]) -> _LabelKey:
    return tuple(sorted((labels or {}).items()))


def _escape_label_value(v: str) -> str:
    """Prometheus exposition escaping: one bad user label value must not
    invalidate the whole scrape."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _render_labels(key: _LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)
    return "{" + inner + "}"


class Metric:
    def __new__(cls, name=None, *args, **kwargs):
        # Re-creating a metric of the SAME name and type returns the
        # registered instance instead of silently replacing it in the
        # registry — the old behaviour orphaned the first object, so
        # modules still incrementing it never rendered again. The
        # get-or-create is ONE critical section: registration happens
        # here, not in __init__, so two racing first-creators cannot
        # both see "absent" and leave one holding an unregistered
        # orphan whose increments never render.
        registry = kwargs.get("registry")
        if registry is None:
            for a in args:
                if isinstance(a, Registry):
                    registry = a
                    break
        if registry is None:
            registry = default_registry()
        with registry._lock:
            existing = registry._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls:
                    raise ValueError(
                        f"metric {name!r} already registered with a "
                        f"different type")
                return existing
            inst = super().__new__(cls)
            registry._metrics[name] = inst
            return inst

    def __init__(self, name: str, description: str, registry: "Registry"):
        if getattr(self, "_registered", False):
            return  # reused instance: keep its recorded state
        self.name = name
        self.description = description
        self._lock = threading.Lock()
        self._init_state()
        self._registered = True

    def _init_state(self) -> None:
        """Subclass hook creating the value stores (runs exactly once per
        registered instance — re-construction must not wipe them)."""

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        """Current value for one label set (counter-based assertions in
        tests; 0.0 when the series was never set/incremented). Only
        meaningful for single-valued metrics (Counter/Gauge)."""
        with self._lock:
            return getattr(self, "_values", {}).get(_label_key(labels), 0.0)

    def total(self) -> float:
        """Sum over every label set (Counter/Gauge): the
        "did ANY series move" form counter-based tests need — e.g. the
        compiled-graph suite proves a steady-state step issues zero
        control RPCs by snapshotting the rpc client-call counter's total
        across all method labels."""
        with self._lock:
            return float(sum(getattr(self, "_values", {}).values()))


class Counter(Metric):
    TYPE = "counter"

    def __init__(self, name, description="", registry=None):
        super().__init__(name, description, registry or default_registry())

    def _init_state(self) -> None:
        self._values: Dict[_LabelKey, float] = {}

    def inc(self, value: float = 1.0,
            labels: Optional[Dict[str, str]] = None) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def render(self) -> List[str]:
        with self._lock:
            return [
                f"{self.name}{_render_labels(k)} {v}"
                for k, v in sorted(self._values.items())
            ]


class Gauge(Metric):
    TYPE = "gauge"

    def __init__(self, name, description="", registry=None):
        super().__init__(name, description, registry or default_registry())

    def _init_state(self) -> None:
        self._values: Dict[_LabelKey, float] = {}

    def set(self, value: float,
            labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, value: float = 1.0, labels=None) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def dec(self, value: float = 1.0, labels=None) -> None:
        self.inc(-value, labels)

    def render(self) -> List[str]:
        with self._lock:
            return [
                f"{self.name}{_render_labels(k)} {v}"
                for k, v in sorted(self._values.items())
            ]


DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   60.0, 300.0)


class _HistogramTimer:
    """Context manager recording a wall-clock span into a Histogram."""

    def __init__(self, hist: "Histogram", labels: Optional[Dict[str, str]]):
        self._hist = hist
        self._labels = labels
        self._t0 = 0.0

    def __enter__(self) -> "_HistogramTimer":
        import time

        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        import time

        self._hist.observe(time.perf_counter() - self._t0, self._labels)


class Histogram(Metric):
    TYPE = "histogram"

    def __init__(self, name, description="",
                 buckets: Sequence[float] = DEFAULT_BUCKETS, registry=None):
        if not getattr(self, "_registered", False):
            self.buckets = tuple(sorted(buckets))
        super().__init__(name, description, registry or default_registry())

    def _init_state(self) -> None:
        self._counts: Dict[_LabelKey, List[int]] = {}
        self._sums: Dict[_LabelKey, float] = {}
        self._totals: Dict[_LabelKey, int] = {}

    def observe(self, value: float,
                labels: Optional[Dict[str, str]] = None) -> None:
        key = _label_key(labels)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            if idx < len(counts):
                counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def time(self, labels: Optional[Dict[str, str]] = None) -> _HistogramTimer:
        """``with hist.time():`` — observe the block's wall-clock seconds
        (the collective round / RPC latency idiom)."""
        return _HistogramTimer(self, labels)

    def count_total(self) -> int:
        """Observations across every label set — the "did this span get
        recorded at all" form counter-based tests need (e.g. proving
        CollectiveWork.wait() instrumented its block)."""
        with self._lock:
            return int(sum(self._totals.values()))

    def sum_total(self) -> float:
        """Sum of observed values across every label set (overlap-fraction
        arithmetic: wait_seconds.sum_total() / round_seconds.sum_total())."""
        with self._lock:
            return float(sum(self._sums.values()))

    def render(self) -> List[str]:
        out: List[str] = []
        with self._lock:
            for key, counts in sorted(self._counts.items()):
                cumulative = 0
                for bound, c in zip(self.buckets, counts):
                    cumulative += c
                    lk = dict(key)
                    lk["le"] = repr(bound)
                    out.append(
                        f"{self.name}_bucket{_render_labels(_label_key(lk))}"
                        f" {cumulative}")
                lk = dict(key)
                lk["le"] = "+Inf"
                out.append(
                    f"{self.name}_bucket{_render_labels(_label_key(lk))}"
                    f" {self._totals[key]}")
                out.append(
                    f"{self.name}_sum{_render_labels(key)} {self._sums[key]}")
                out.append(
                    f"{self.name}_count{_render_labels(key)} "
                    f"{self._totals[key]}")
        return out


class Registry:
    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def render_prometheus(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            if not getattr(m, "_registered", False):
                continue  # registered in __new__, still mid-__init__
            lines.append(f"# HELP {m.name} {m.description}")
            lines.append(f"# TYPE {m.name} {m.TYPE}")
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


def relabel_exposition(text: str, extra: Dict[str, str]) -> str:
    """Inject labels into every sample line of a Prometheus exposition —
    the cluster-wide scrape merge (`util.state.cluster_metrics(
    all_nodes=True)`) stamps ``node``/``component`` onto each daemon's
    text so identically-named series stay distinguishable."""
    extra_str = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(extra.items()))
    out: List[str] = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        # the value never contains spaces; label VALUES may, so split at
        # the last space only
        try:
            left, value = line.rsplit(" ", 1)
        except ValueError:
            out.append(line)
            continue
        if left.endswith("}"):
            out.append(f"{left[:-1]},{extra_str}}} {value}")
        else:
            out.append(f"{left}{{{extra_str}}} {value}")
    return "\n".join(out)


def merge_expositions(parts: Iterable[str]) -> str:
    """Merge already-relabelled expositions into parser-valid Prometheus
    text. A metric family present in several processes (most are) must
    render as ONE ``# HELP``/``# TYPE`` block with every part's samples
    grouped under it — the exposition format rejects duplicate TYPE
    lines and split families, so a plain concatenation scrapes fine by
    eye but fails promtool/Prometheus ingestion."""
    help_lines: Dict[str, str] = {}
    type_lines: Dict[str, str] = {}
    samples: Dict[str, List[str]] = {}
    order: List[str] = []

    def bucket(family: str) -> List[str]:
        got = samples.get(family)
        if got is None:
            got = samples[family] = []
            order.append(family)
        return got

    for text in parts:
        family = ""  # samples before any header stay in one '' bucket
        for line in text.splitlines():
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                family = line.split(" ", 3)[2]
                target = help_lines if line.startswith("# HELP ") \
                    else type_lines
                target.setdefault(family, line)
                bucket(family)
            elif not line or line.startswith("#"):
                continue
            else:
                # our renderers emit samples directly under their
                # family's header block, so `family` still names it
                bucket(family).append(line)
    out: List[str] = []
    for fam in order:
        if fam in help_lines:
            out.append(help_lines[fam])
        if fam in type_lines:
            out.append(type_lines[fam])
        out.extend(samples[fam])
    return "\n".join(out) + "\n"


_default: Optional[Registry] = None
_default_lock = threading.Lock()


def default_registry() -> Registry:
    global _default
    with _default_lock:
        if _default is None:
            _default = Registry()
        return _default
