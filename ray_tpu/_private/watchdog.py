"""Owner-liveness watchdog: daemons exit when the process that spawned
them dies.

Analog of the reference raylet noticing a client disconnect
(`src/ray/raylet/node_manager.cc:1432` DisconnectClient) and the GCS
health-checking nodes (`src/ray/gcs/gcs_server/gcs_health_check_manager.h:39`):
a SIGKILLed driver must not orphan its controller/supervisor/worker tree.
On a single-client TPU tunnel an orphaned worker holding the TPU wedges
every subsequent run, so this is load-bearing, not cosmetic.

Chain of custody: the driver spawns controller+supervisors with
``RAY_TPU_OWNER_PID`` = driver pid; the supervisor re-stamps worker envs
with its own pid. Each process polls its owner every
``RAY_TPU_WATCHDOG_INTERVAL_S`` (default 1s) and hard-exits when the
owner is gone, so a killed driver collapses the whole tree within ~2
poll intervals. Pid-reuse is guarded by comparing the owner's
``/proc/<pid>/stat`` start time recorded at spawn.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Optional

logger = logging.getLogger(__name__)

ENV_OWNER_PID = "RAY_TPU_OWNER_PID"
ENV_OWNER_START = "RAY_TPU_OWNER_START"
ENV_DISABLE = "RAY_TPU_OWNER_WATCHDOG"  # set to "0" to disable


def proc_start_time(pid: int) -> Optional[int]:
    """Kernel start time (clock ticks since boot) of *pid*, or None if the
    process does not exist. Field 22 of /proc/<pid>/stat; the comm field
    may contain spaces/parens, so parse after the last ')'."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            data = f.read()
    except OSError:
        return None
    try:
        rest = data[data.rindex(b")") + 2 :].split()
        # rest[0] is field 3 (state); start time is field 22 -> rest[19]
        return int(rest[19])
    except Exception:
        return None


def owner_env(env: dict) -> dict:
    """Stamp *env* so a child started with it watches THIS process."""
    env[ENV_OWNER_PID] = str(os.getpid())
    start = proc_start_time(os.getpid())
    if start is not None:
        env[ENV_OWNER_START] = str(start)
    return env


def _owner_alive(pid: int, expect_start: Optional[int]) -> bool:
    start = proc_start_time(pid)
    if start is None:
        return False
    if expect_start is not None and start != expect_start:
        return False  # pid reused by an unrelated process
    return True


def _kill_children(sig: int = signal.SIGTERM) -> None:
    """Best-effort signal to our direct children (their own watchdogs —
    which watch us — finish the job for grandchildren)."""
    me = os.getpid()
    try:
        pids = [int(d) for d in os.listdir("/proc") if d.isdigit()]
    except OSError:
        return
    for pid in pids:
        try:
            with open(f"/proc/{pid}/stat", "rb") as f:
                data = f.read()
            ppid = int(data[data.rindex(b")") + 2 :].split()[1])
            if ppid == me:
                os.kill(pid, sig)
        except Exception:
            continue


def start_owner_watchdog_from_env(label: str = "") -> Optional[threading.Thread]:
    """Start the watchdog thread if RAY_TPU_OWNER_PID is set (and the
    watchdog isn't disabled). Called from every daemon/worker main()."""
    if os.environ.get(ENV_DISABLE, "1") == "0":
        return None
    raw = os.environ.get(ENV_OWNER_PID, "")
    if not raw:
        return None
    try:
        owner = int(raw)
    except ValueError:
        return None
    expect_start: Optional[int] = None
    raw_start = os.environ.get(ENV_OWNER_START, "")
    if raw_start:
        try:
            expect_start = int(raw_start)
        except ValueError:
            expect_start = None
    interval = float(os.environ.get("RAY_TPU_WATCHDOG_INTERVAL_S", "1.0"))

    def run() -> None:
        while True:
            if not _owner_alive(owner, expect_start):
                logger.warning(
                    "%s: owner pid %d is gone; exiting", label or "watchdog", owner
                )
                _kill_children()
                # os._exit: the owner is dead, nobody is listening; a
                # graceful asyncio teardown can itself hang on the wedged
                # resource we exist to release.
                os._exit(78)
            time.sleep(interval)

    t = threading.Thread(target=run, name="owner-watchdog", daemon=True)
    t.start()
    return t
