"""Node-local shared-memory object store.

TPU-native analog of the reference's plasma store
(`src/ray/object_manager/plasma/store.h`, allocator `plasma_allocator.h` /
`dlmalloc.cc`, lifecycle `object_lifecycle_manager.h`): one immutable-object
arena per host, shared between the supervisor and every worker/driver process
on that host.

Design:
  * Backing is a single sparse file in /dev/shm, mmapped by every process that
    touches objects (`ArenaFile`). One mapping per process for its lifetime —
    no per-object mmap churn, no resource-tracker interference.
  * The supervisor owns allocation metadata (`NodeObjectStore`): a first-fit
    free-list allocator with coalescing (stand-in for the dlmalloc arena; the
    C++ allocator in src/ replaces it without changing the protocol).
  * Clients create (RPC → offset), write payload bytes directly into the
    mapping, then seal. Reads locate (RPC → offset,size, pin=True) and
    deserialize ZERO-COPY: out-of-band payload buffers become read-only
    views over the reader's own mapping, and the pin — tracked per client
    so a crashed reader's pins can be reclaimed — protects the range from
    spill/free until the last view is garbage-collected
    (≈ plasma's get/release pinning; see core_worker._read_shared).
  * Spilling under memory pressure moves sealed, unreferenced objects to disk
    (analog of `external_storage.py:185`), restored on demand.

Host RAM only: device arrays never transit this store — they stay in HBM
inside the owning process and move over ICI via XLA collectives.
"""

from __future__ import annotations

import dataclasses
import mmap
import os
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.ids import ObjectID

PAGE = 4096


def _align(n: int) -> int:
    return (n + PAGE - 1) // PAGE * PAGE


class ArenaFile:
    """A process-local mmap of the node's object arena."""

    def __init__(self, path: str, size: int, create: bool = False):
        self.path = path
        self.size = size
        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        fd = os.open(path, flags, 0o600)
        try:
            if create:
                os.ftruncate(fd, size)
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)

    def view(self, offset: int, length: int) -> memoryview:
        return memoryview(self._mm)[offset : offset + length]

    def write(self, offset: int, data) -> None:
        self._mm[offset : offset + len(data)] = data

    def close(self) -> None:
        try:
            self._mm.close()
        except Exception:
            pass

    def unlink(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass


class OutOfMemoryError(Exception):
    pass


class _NativeFreeList:
    """ctypes binding of the C++ arena allocator (_native/allocator.cpp):
    O(log n) coalescing plus double-free/overlap validation. Selected by
    make_free_list() when the native lib builds; same surface as
    _FreeList."""

    def __init__(self, capacity: int, lib):
        import ctypes

        self.capacity = capacity
        self._lib = lib
        lib.rtpu_alloc_create.restype = ctypes.c_void_p
        lib.rtpu_alloc_create.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
        lib.rtpu_alloc_alloc.restype = ctypes.c_int64
        lib.rtpu_alloc_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rtpu_alloc_free.restype = ctypes.c_int
        lib.rtpu_alloc_free.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64]
        lib.rtpu_alloc_free_bytes.restype = ctypes.c_uint64
        lib.rtpu_alloc_free_bytes.argtypes = [ctypes.c_void_p]
        lib.rtpu_alloc_destroy.argtypes = [ctypes.c_void_p]
        self._handle = lib.rtpu_alloc_create(capacity, PAGE)
        if not self._handle:
            raise MemoryError("native allocator init failed")

    def alloc(self, size: int) -> Optional[int]:
        off = self._lib.rtpu_alloc_alloc(self._handle, size)
        return None if off < 0 else off

    def free(self, offset: int, size: int) -> None:
        rc = self._lib.rtpu_alloc_free(self._handle, offset, size)
        if rc == -2:
            raise ValueError(
                f"double/overlapping free at offset={offset} size={size}")
        if rc != 0:
            raise ValueError(f"invalid free offset={offset} size={size}")

    def free_bytes(self) -> int:
        return self._lib.rtpu_alloc_free_bytes(self._handle)

    def __del__(self):
        try:
            self._lib.rtpu_alloc_destroy(self._handle)
        except Exception:
            pass


def make_free_list(capacity: int):
    """Native allocator when the toolchain allows, Python otherwise."""
    if os.environ.get("RAY_TPU_DISABLE_NATIVE", "") not in ("1", "true"):
        try:
            from ray_tpu._native import load_library

            lib = load_library("allocator")
            if lib is not None:
                return _NativeFreeList(capacity, lib)
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "native allocator unavailable; using the Python free "
                "list (no double-free detection)", exc_info=True)
    return _FreeList(capacity)


class _FreeList:
    """First-fit free-list allocator over [0, capacity) with coalescing.
    Pure-Python fallback for make_free_list()."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        # sorted list of (offset, size) free ranges
        self._free: List[Tuple[int, int]] = [(0, capacity)]

    def alloc(self, size: int) -> Optional[int]:
        size = _align(size)
        for i, (off, sz) in enumerate(self._free):
            if sz >= size:
                if sz == size:
                    self._free.pop(i)
                else:
                    self._free[i] = (off + size, sz - size)
                return off
        return None

    def free(self, offset: int, size: int) -> None:
        size = _align(size)
        # insert sorted, coalesce neighbors
        lo, hi = 0, len(self._free)
        while lo < hi:
            mid = (lo + hi) // 2
            if self._free[mid][0] < offset:
                lo = mid + 1
            else:
                hi = mid
        self._free.insert(lo, (offset, size))
        merged: List[Tuple[int, int]] = []
        for off, sz in self._free:
            if merged and merged[-1][0] + merged[-1][1] == off:
                merged[-1] = (merged[-1][0], merged[-1][1] + sz)
            else:
                merged.append((off, sz))
        self._free = merged

    def free_bytes(self) -> int:
        return sum(sz for _, sz in self._free)


IN_MEMORY = "IN_MEMORY"
SPILLED = "SPILLED"
CREATING = "CREATING"


@dataclasses.dataclass
class ObjectMeta:
    object_id: ObjectID
    size: int
    state: str = CREATING
    offset: int = -1
    spill_path: str = ""
    last_access: float = 0.0
    freed: bool = False  # owner released it; eligible for deletion
    pins: int = 0  # readers holding views over the arena; blocks spill/free
    # pin counts per client id (worker/driver/puller) so the pins of a
    # crashed client can be released instead of blocking spill forever
    pin_clients: Dict[str, int] = dataclasses.field(default_factory=dict)


class NodeObjectStore:
    """Supervisor-side object index + allocator (single-threaded: runs on the
    supervisor's event loop)."""

    def __init__(self, arena_path: str, capacity: int, spill_dir: str,
                 spill_storage=None):
        self.capacity = capacity
        self.arena = ArenaFile(arena_path, capacity, create=True)
        self._alloc = make_free_list(capacity)
        self._objects: Dict[ObjectID, ObjectMeta] = {}
        if spill_storage is None:
            from ray_tpu._private.external_storage import FileSystemStorage

            spill_storage = FileSystemStorage(spill_dir)
        # pluggable spill target (≈ external_storage.py:496): local dir by
        # default, mock:// fake remote in tests, s3:// in deployments
        self.spill_storage = spill_storage
        self.num_spilled = 0
        self.num_restored = 0
        # reverse index: pin key -> object ids it currently pins (release
        # path for dead clients; see release_client_pins). Keys are the
        # client id suffixed with its incarnation epoch (plain id at
        # epoch 0): reusable client ids ("node:<hex>") bump their epoch
        # when a released client comes back, so a DELAYED bulk release
        # scheduled for the old incarnation can never reclaim pins the
        # new incarnation just took
        self._client_pins: Dict[str, set] = {}
        self._client_epoch: Dict[str, int] = {}

    # ---- creation ----

    def create(self, object_id: ObjectID, size: int) -> int:
        """Reserve space; returns arena offset. Spills/evicts under pressure."""
        if object_id in self._objects:
            meta = self._objects[object_id]
            if meta.state != CREATING:
                raise ValueError(f"object {object_id.hex()} already exists")
            return meta.offset
        offset = self._alloc_with_spill(size)
        if offset is None:
            raise OutOfMemoryError(
                f"object store full: need {size}, free {self._alloc.free_bytes()}"
            )
        self._objects[object_id] = ObjectMeta(
            object_id, size, CREATING, offset, last_access=time.monotonic()
        )
        return offset

    def create_channel(self, object_id: ObjectID, size: int,
                       client: str) -> int:
        """Allocate a compiled-graph channel range: create + seal + pin in
        ONE store op, so there is no window in which the freshly sealed
        range could be spilled (which would move its offset) before the
        pin lands. The pin is attributed to ``client`` (the compiling
        driver) exactly like a zero-copy read pin — release_client_pins
        reclaims it if the driver dies, and the channel object itself is
        freed through the normal deferred-free path once every
        participant's pin is gone."""
        offset = self.create(object_id, size)
        meta = self._objects[object_id]
        meta.state = IN_MEMORY
        meta.pins += 1
        key = self._pin_key(client)
        meta.pin_clients[key] = meta.pin_clients.get(key, 0) + 1
        self._client_pins.setdefault(key, set()).add(object_id)
        meta.last_access = time.monotonic()
        return offset

    # ---- incarnation-keyed pin accounting ----

    def _pin_key(self, client: str) -> str:
        """Effective accounting key for ``client``'s CURRENT incarnation
        (plain id at epoch 0 — the common never-bumped case)."""
        e = self._client_epoch.get(client, 0)
        return client if e == 0 else f"{client}#e{e}"

    @staticmethod
    def _pin_key_client(key: str) -> str:
        return key.rsplit("#e", 1)[0] if "#e" in key else key

    @staticmethod
    def _pin_key_epoch(key: str) -> int:
        if "#e" in key:
            tail = key.rsplit("#e", 1)[1]
            if tail.isdigit():
                return int(tail)
        return 0

    def client_epoch(self, client: str) -> int:
        return self._client_epoch.get(client, 0)

    def bump_client_epoch(self, client: str) -> int:
        """A previously-released client id is back (node flap, same
        ``node:<hex>`` id): start a fresh incarnation so its new pins are
        keyed apart from any still-pending bulk release of the old one."""
        e = self._client_epoch.get(client, 0) + 1
        self._client_epoch[client] = e
        return e

    def seal(self, object_id: ObjectID) -> None:
        meta = self._objects.get(object_id)
        if meta is None:
            raise KeyError(f"seal of unknown object {object_id.hex()}")
        meta.state = IN_MEMORY
        meta.last_access = time.monotonic()

    def abort(self, object_id: ObjectID) -> None:
        meta = self._objects.pop(object_id, None)
        if meta is not None and meta.offset >= 0:
            self._alloc.free(meta.offset, meta.size)

    # ---- reads ----

    def contains(self, object_id: ObjectID) -> bool:
        m = self._objects.get(object_id)
        return m is not None and m.state in (IN_MEMORY, SPILLED)

    def locate(self, object_id: ObjectID, pin: bool = False,
               client: str = "") -> Optional[Tuple[int, int]]:
        """Return (offset, size), restoring from spill if needed.

        With pin=True the range is protected from spill/free until unpin() —
        readers deserialize zero-copy views over their own mmap after the
        RPC returns, so the range must not be recycled while any view is
        alive (≈ plasma's get/release pinning). Pins are attributed to
        ``client`` so release_client_pins() can reclaim the pins of a
        crashed reader.
        """
        meta = self._objects.get(object_id)
        if meta is None or meta.state == CREATING:
            return None
        if meta.state == SPILLED:
            self._restore(meta)
        meta.last_access = time.monotonic()
        if pin:
            meta.pins += 1
            key = self._pin_key(client)
            meta.pin_clients[key] = meta.pin_clients.get(key, 0) + 1
            self._client_pins.setdefault(key, set()).add(object_id)
        return (meta.offset, meta.size)

    def pinned_clients(self) -> List[str]:
        """Client ids currently holding pins (liveness-sweep input) —
        raw ids, every incarnation folded together."""
        return sorted({self._pin_key_client(k) for k in self._client_pins})

    def unpin(self, object_id: ObjectID, client: str = "") -> bool:
        """Release one pin held by ``client``. An unpin with no matching
        pin is a protocol bug (double-unpin, or unpin of a never-pinned
        object) and raises — bulk reclamation for dead/departing clients
        goes through release_client_pins() instead."""
        meta = self._objects.get(object_id)
        key = None
        if meta is not None:
            # current incarnation first; a pin taken under an older
            # epoch (owner outlived a flap-back bump) still matches
            cur = self._pin_key(client)
            if meta.pin_clients.get(cur, 0) > 0:
                key = cur
            else:
                for k in meta.pin_clients:
                    if (self._pin_key_client(k) == client
                            and meta.pin_clients[k] > 0):
                        key = k
                        break
        if meta is None or meta.pins <= 0 or key is None:
            raise ValueError(
                f"unpin without matching pin: object="
                f"{object_id.hex()[:16]} client={client!r} "
                f"(double-unpin or unpin of a never-pinned object)")
        meta.pins -= 1
        remaining = meta.pin_clients[key] - 1
        if remaining > 0:
            meta.pin_clients[key] = remaining
        else:
            del meta.pin_clients[key]
            held = self._client_pins.get(key)
            if held is not None:
                held.discard(object_id)
                if not held:
                    self._client_pins.pop(key, None)
        if meta.freed and meta.pins == 0:
            self.free(object_id)
        return True

    def release_client_pins(self, client: str,
                            before_epoch: Optional[int] = None) -> int:
        """Drop every pin held by ``client`` (it died without unpinning).
        Returns the number of pins released; deferred frees fire for
        objects whose last pin this was.

        ``before_epoch`` bounds the release to incarnations BELOW that
        epoch: the dead-client sweep captures ``client_epoch() + 1`` when
        the death is observed, so a release that runs late — after the
        same client id re-registered and was epoch-bumped — reclaims
        only the dead incarnation's pins, never the pins the new
        incarnation just took. ``None`` releases every incarnation (the
        graceful departing-client path)."""
        released = 0
        keys = [k for k in self._client_pins
                if self._pin_key_client(k) == client
                and (before_epoch is None
                     or self._pin_key_epoch(k) < before_epoch)]
        for key in keys:
            for object_id in self._client_pins.pop(key, set()):
                meta = self._objects.get(object_id)
                if meta is None:
                    continue
                count = meta.pin_clients.pop(key, 0)
                meta.pins = max(0, meta.pins - count)
                released += count
                if meta.freed and meta.pins == 0:
                    self.free(object_id)
        return released

    def read_chunk(self, object_id: ObjectID, offset: int, length: int) -> bytes:
        loc = self.locate(object_id)
        if loc is None:
            raise KeyError(f"object {object_id.hex()} not in store")
        base, size = loc
        length = min(length, size - offset)
        return bytes(self.arena.view(base + offset, length))

    # ---- lifecycle ----

    def free(self, object_id: ObjectID) -> None:
        """Owner released the object: delete its data (deferred while pinned)."""
        meta = self._objects.get(object_id)
        if meta is None:
            return
        if meta.pins > 0:
            meta.freed = True
            return
        self._objects.pop(object_id, None)
        if meta.state == SPILLED and meta.spill_path:
            self.spill_storage.delete(meta.spill_path)
        elif meta.offset >= 0:
            self._alloc.free(meta.offset, meta.size)

    def _alloc_with_spill(self, need: int) -> Optional[int]:
        """Allocate `need` bytes, spilling least-recently-used sealed
        objects as required. Retries the allocation as objects spill:
        total free bytes are NOT enough — the allocator needs one
        CONTIGUOUS range, and a GiB-class restore into an arena dotted
        with small live objects only succeeds once the spills have
        coalesced a large-enough hole (the fragmentation case the old
        free_bytes()-threshold check missed)."""
        offset = self._alloc.alloc(need)
        if offset is not None:
            return offset
        candidates = sorted(
            (
                m
                for m in self._objects.values()
                if m.state == IN_MEMORY and m.pins == 0
            ),
            key=lambda m: m.last_access,
        )
        aligned = _align(need)
        for meta in candidates:
            self._spill(meta)
            if self._alloc.free_bytes() >= aligned:
                offset = self._alloc.alloc(need)
                if offset is not None:
                    return offset
        return self._alloc.alloc(need)

    def _spill(self, meta: ObjectMeta) -> None:
        # pass the arena view straight through (bytes-like): spilling
        # fires under memory pressure, so a full bytes copy of a multi-GB
        # object here would double transient memory at the worst moment
        uri = self.spill_storage.put(
            meta.object_id.hex(), self.arena.view(meta.offset, meta.size))
        self._alloc.free(meta.offset, meta.size)
        meta.offset = -1
        meta.spill_path = uri  # opaque backend URI, not a local path
        meta.state = SPILLED
        self.num_spilled += 1

    def _restore(self, meta: ObjectMeta) -> None:
        offset = self._alloc_with_spill(meta.size)
        if offset is None:
            raise OutOfMemoryError("cannot restore spilled object: store full")
        self.arena.write(offset, self.spill_storage.get(meta.spill_path))
        self.spill_storage.delete(meta.spill_path)
        meta.offset = offset
        meta.spill_path = ""
        meta.state = IN_MEMORY
        self.num_restored += 1

    def stats(self) -> Dict[str, float]:
        # snapshot first: stats() is read from metric/sync paths off the
        # store thread; iterating the live dict would race its mutations
        metas = list(self._objects.values())
        in_mem = sum(1 for m in metas if m.state == IN_MEMORY)
        spilled = sum(1 for m in metas if m.state == SPILLED)
        return {
            "capacity": self.capacity,
            "free_bytes": self._alloc.free_bytes(),
            "num_objects": len(self._objects),
            "num_in_memory": in_mem,
            "num_spilled_now": spilled,
            "total_spills": self.num_spilled,
            "total_restores": self.num_restored,
            "pinned_objects": sum(1 for m in metas if m.pins > 0),
            "pins_total": sum(m.pins for m in metas),
        }

    def shutdown(self) -> None:
        self.arena.unlink()


class InProcessStore:
    """Per-CoreWorker store for small objects and pending futures.

    Analog of the reference's in-process memory store
    (`core_worker/store_provider/memory_store/`): small task returns and puts
    live here in the owner process; remote readers fetch them from the owner
    over RPC.
    """

    def __init__(self):
        self._values: Dict[ObjectID, bytes] = {}  # packed payloads

    def put(self, object_id: ObjectID, packed: bytes) -> None:
        self._values[object_id] = packed

    def get(self, object_id: ObjectID) -> Optional[bytes]:
        return self._values.get(object_id)

    def contains(self, object_id: ObjectID) -> bool:
        return object_id in self._values

    def free(self, object_id: ObjectID) -> None:
        self._values.pop(object_id, None)

    def __len__(self) -> int:
        return len(self._values)
