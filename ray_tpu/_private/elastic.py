"""Elastic respawn policy: preemption-tolerant worker replacement.

``ElasticSupervisor`` is the per-workload policy object behind ISSUE
16's membership layer: when a dp replica / env-runner dies (node
preemption, chaos kill), the workload asks this policy whether and when
to respawn a replacement — a bounded respawn budget so a crash-looping
spec cannot spin forever, exponential backoff between attempts on the
SAME slot so a flapping node is not hammered, and placement resolved
through the existing ``channels.resolve_actor_placement`` so the
replacement's channels land exactly like the original's did.

The policy is deliberately dumb about *what* to spawn — the workload
passes a zero-arg ``spawn_fn`` that runs its own actor-options path —
and strict about *accounting*: every departure, respawn and rejoin is
counted (``ray_tpu_elastic_{departures,joins,reshards}_total``), and
rejoin latency (death observed -> replacement serving at the new epoch)
lands in the ``ray_tpu_elastic_rejoin_seconds`` histogram plus an
``elastic.rejoin`` flight span, so a soak can assert elasticity's cost
the same way it asserts its correctness.

Knobs (config fields / env):

  * ``RAY_TPU_ELASTIC_RESPAWN_BUDGET`` — max respawns per slot for the
    workload's lifetime.
  * ``RAY_TPU_ELASTIC_BACKOFF_S`` — base backoff; attempt n on a slot
    waits ``backoff * 2**(n-1)`` seconds (capped at 30s).
  * ``RAY_TPU_ELASTIC_RESIZE_TIMEOUT_S`` — budget for the post-resize
    first operation (survivor re-rendezvous + joiner param sync).

All three reject explicit zeros loudly (``require_positive`` — the
recurring PR-8/9/13 falsy-zero ``or``-chain lesson): 0 never silently
means "some default", it raises at build time.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu._private import flight
from ray_tpu._private.metrics import Counter, Histogram

logger = logging.getLogger(__name__)

_F_REJOIN = flight.intern("elastic.rejoin")

m_joins = Counter(
    "ray_tpu_elastic_joins_total",
    "Replacement workers spawned and rejoined after a departure")
m_departures = Counter(
    "ray_tpu_elastic_departures_total",
    "Members lost from elastic groups (death fan-out observed)")
m_reshards = Counter(
    "ray_tpu_elastic_reshards_total",
    "Elastic group re-declarations (shrink or grow) at a new epoch")
m_rejoin_seconds = Histogram(
    "ray_tpu_elastic_rejoin_seconds",
    "Departure-observed to replacement-serving latency",
    buckets=(0.5, 1, 2, 5, 10, 30, 60, 120))


def require_positive(name: str, value, kind=int):
    """Validate an elastic knob: explicit zeros (and negatives) RAISE
    instead of falling through a falsy-``or`` chain to some default."""
    if value is None:
        raise ValueError(f"{name} must be set")
    v = kind(value)
    if v <= 0:
        raise ValueError(
            f"{name} must be a positive {kind.__name__}, got {value!r} "
            f"(explicit zeros are rejected, never silently replaced "
            f"with a default)")
    return v


_BACKOFF_CAP_S = 30.0


class ElasticSupervisor:
    """Respawn budget + backoff + placement for one elastic workload.

    Thread-safe; one instance per topology (``PipelineTrainer``,
    ``SebulbaTopology``). Slots are caller-chosen keys (e.g.
    ``("dp", 2)`` or ``"runner3"``) so the budget is per-position, not
    global — losing every dp row once is N respawns of budget 1 each,
    not one slot burning the whole budget.
    """

    def __init__(self, *, respawn_budget: Optional[int] = None,
                 backoff_s: Optional[float] = None,
                 resize_timeout_s: Optional[float] = None,
                 config=None, name: str = "elastic"):
        if config is None:
            from ray_tpu._private.config import global_config

            config = global_config()
        if respawn_budget is None:
            respawn_budget = config.elastic_respawn_budget
        if backoff_s is None:
            backoff_s = config.elastic_backoff_s
        if resize_timeout_s is None:
            resize_timeout_s = config.elastic_resize_timeout_s
        self.name = name
        self.respawn_budget = require_positive(
            "elastic_respawn_budget", respawn_budget)
        self.backoff_s = require_positive(
            "elastic_backoff_s", backoff_s, kind=float)
        self.resize_timeout_s = require_positive(
            "elastic_resize_timeout_s", resize_timeout_s, kind=float)
        self._lock = threading.Lock()
        self._attempts: Dict[Any, int] = {}

    @property
    def resize_timeout_ms(self) -> int:
        return int(self.resize_timeout_s * 1000)

    def attempts(self, slot: Any) -> int:
        with self._lock:
            return self._attempts.get(slot, 0)

    def respawn(self, slot: Any, spawn_fn: Callable[[], Any]) -> Any:
        """Spawn slot's replacement under the budget/backoff policy.

        Raises ``RuntimeError`` when the slot's budget is exhausted —
        the workload then surfaces the clean terminal error chaos_soak
        expects for non-recoverable schedules. Sleeps out the
        exponential backoff (caller's thread: respawn happens at a
        flush/step boundary, which is exactly where the workload is
        allowed to stall)."""
        with self._lock:
            n = self._attempts.get(slot, 0) + 1
            if n > self.respawn_budget:
                raise RuntimeError(
                    f"elastic respawn budget exhausted for slot {slot!r} "
                    f"({self.respawn_budget} respawn(s)); treating the "
                    f"departure as terminal")
            self._attempts[slot] = n
        if n > 1:
            delay = min(self.backoff_s * 2 ** (n - 2), _BACKOFF_CAP_S)
            logger.info("elastic %s: slot %r respawn attempt %d, backing "
                        "off %.1fs", self.name, slot, n, delay)
            time.sleep(delay)
        actor = spawn_fn()
        m_joins.inc(labels={"workload": self.name})
        return actor

    def resolve_placement(self, core, actor, views) -> dict:
        """Where did the replacement land (worker/node identity for
        channel participant sets) — the existing placement path, one
        name."""
        from ray_tpu._private import channels as _channels

        return _channels.resolve_actor_placement(core, actor._actor_id,
                                                 views)

    def rejoin_span(self, started_monotonic: float) -> None:
        """Record one completed rejoin (departure observed at
        ``started_monotonic`` -> replacement serving now)."""
        dt = max(0.0, time.monotonic() - started_monotonic)
        m_rejoin_seconds.observe(dt, labels={"workload": self.name})
        t_now = flight.now()
        if t_now:
            flight.span_since(_F_REJOIN, max(1, t_now - int(dt * 1e9)))
