"""Namespace-sharded controller KV.

First step toward a scale-out control plane (ROADMAP item 1): the
controller's internal KV — function table, collective rendezvous, serve
weights claims, PG readiness mirror — is partitioned into N in-process
shards by namespace hash. Each shard owns its table, its own mutation
lock, and its own named WAL stream in the control store
(``gcs_store`` stream ``kv<i>``), so:

  * KV mutations in different shards fsync their WAL frames
    concurrently (the appends run on different executor threads under
    different locks) instead of serializing behind one log;
  * a shard is already a self-contained unit — table + lock + durable
    log — which is exactly the boundary a later PR needs to move shards
    out of the controller process (the reference's Redis-backed GCS
    store client shape, ``redis_store_client.h``).

Routing is a pure function of (namespace, shard count): every key of a
namespace lives in one shard, so ``kv_keys(prefix)`` and the kv_wait
notify path never fan out. Snapshots store the MERGED dict and
redistribute on load, so changing ``controller_kv_shards`` between
controller incarnations is safe.
"""

from __future__ import annotations

import asyncio
import zlib
from typing import Any, Dict, List


def shard_index(ns: str, num_shards: int) -> int:
    """Stable shard routing: crc32 of the namespace (NOT Python's
    ``hash``, which is salted per process — two controller incarnations
    must route identically or recovery would look up the wrong shard)."""
    if num_shards <= 1:
        return 0
    return zlib.crc32(ns.encode("utf-8", "surrogatepass")) % num_shards


class KvShard:
    """One shard: table + mutation lock + WAL stream name."""

    __slots__ = ("index", "stream", "data", "lock")

    def __init__(self, index: int):
        self.index = index
        self.stream = f"kv{index}"
        # ns -> key -> value (same shape the unsharded controller held)
        self.data: Dict[str, Dict[str, Any]] = {}
        self.lock = asyncio.Lock()

    def num_keys(self) -> int:
        return sum(len(d) for d in self.data.values())


class KvShardMap:
    """N in-process KV shards behind the old dict-of-namespaces surface."""

    def __init__(self, num_shards: int = 8):
        if int(num_shards) < 1:
            raise ValueError(
                f"controller_kv_shards must be >= 1, got {num_shards}")
        self.shards: List[KvShard] = [
            KvShard(i) for i in range(int(num_shards))]

    def __len__(self) -> int:
        return len(self.shards)

    def shard_for(self, ns: str) -> KvShard:
        return self.shards[shard_index(ns, len(self.shards))]

    # ---------------------------------------------------------- table access

    def namespace(self, ns: str) -> Dict[str, Any]:
        """The live (mutable) table of one namespace, created on demand —
        the ``kv.setdefault(ns, {})`` shape the controller handlers use."""
        return self.shard_for(ns).data.setdefault(ns, {})

    def peek(self, ns: str) -> Dict[str, Any]:
        """Read-only view of one namespace ({} when absent, NOT created)."""
        return self.shard_for(ns).data.get(ns, {})

    # ------------------------------------------------------ snapshot / load

    def merged(self) -> Dict[str, Dict[str, Any]]:
        """Flat ns->table dict for the controller snapshot: shard-count
        agnostic on disk (a restarted controller with a different shard
        count redistributes on load)."""
        out: Dict[str, Dict[str, Any]] = {}
        for shard in self.shards:
            for ns, table in shard.data.items():
                out[ns] = dict(table)
        return out

    def load(self, merged: Dict[str, Dict[str, Any]]) -> None:
        for shard in self.shards:
            shard.data.clear()
        for ns, table in (merged or {}).items():
            self.shard_for(ns).data[ns] = dict(table)

    # ------------------------------------------------------------- metrics

    def keys_per_shard(self) -> List[int]:
        return [shard.num_keys() for shard in self.shards]

    def total_keys(self) -> int:
        return sum(self.keys_per_shard())

    def num_namespaces(self) -> int:
        return sum(len(shard.data) for shard in self.shards)
