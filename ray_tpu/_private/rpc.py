"""Async RPC substrate for the control plane.

TPU-native analog of the reference's gRPC wrapper layer (`src/ray/rpc/
grpc_server.h`, `grpc_client.h`, `client_call.h`): every daemon (controller,
supervisor, worker) runs one ``RpcServer``; peers hold multiplexed,
auto-reconnecting ``RpcClient``s.

We deliberately do not use gRPC for the control plane: the reference needs
gRPC for cross-language parity (C++/Java/Python all speak the same proto); our
control plane is Python+C++ only and latency-bound by asyncio scheduling, not
marshalling. The wire protocol is length-prefixed pickles over TCP — trivially
inspectable, no proto toolchain in the loop, and the object-payload path never
rides it (objects move via the shared-memory store and the chunked transfer
protocol in object_store.py / supervisor.py).

Frame: [u32 little-endian length][payload]
Payload: pickle of (kind, msg_id, method, body[, client_id])
  kind: 0=request 1=reply 2=error 3=oneway
  client_id: 8 random bytes stable for the client's lifetime; with msg_id it
  forms the exactly-once key for the server's replay cache (requests only;
  replies echo the bare msg_id).

Fault tolerance (what gRPC + the GCS managers give the reference, rebuilt):

  * ``RpcClient.call`` retries transparently on connection loss —
    reconnect, exponential backoff + jitter, the SAME ``msg_id`` resent —
    all under one deadline budget covering connect + request + retries.
  * The server replays cached replies for retried/duplicated deliveries of
    methods registered ``replay_cached=True`` (non-idempotent control RPCs:
    lease grants, task pushes, registrations). A retried ``request_lease``
    whose first reply was lost gets the original grant back instead of
    leasing a second worker. Handlers are annotated at their definition with
    :func:`replay_cached` / :func:`idempotent`.
  * Both sides consult :mod:`ray_tpu._private.chaos` so a seeded
    ``FaultController`` can drop (sever), duplicate, or delay any frame —
    the substrate the chaos suite drives.
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
import logging
import os
import pickle
import random
import socket
import struct
import time
from collections import OrderedDict
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

from ray_tpu._private import serialization
from ray_tpu._private.chaos import fault_controller
from ray_tpu._private.metrics import Counter

logger = logging.getLogger(__name__)

# every outbound RPC this process issues, by method. The compiled-graph
# suite snapshots total() around a steady-state step window to PROVE the
# channel path does zero control-plane RPCs (transparent retries of one
# logical call count once — a retry is not a new control decision).
_m_client_calls = Counter(
    "ray_tpu_rpc_client_calls_total",
    "Outbound RPC calls issued by this process (call + notify), by method")

# every request this process's server DISPATCHES (replay-cache hits
# included), by method: the serve-side twin of the client counter. The
# controller-HA suite scrapes the controller's series to prove the
# steady task loop leases node-locally (0 controller request_lease).
_m_server_requests = Counter(
    "ray_tpu_rpc_server_requests_total",
    "Requests dispatched by this process's RPC server, by method")

# the (client_id, msg_id) replay key of the request currently being
# dispatched, visible to replay-cached handlers: the controller embeds
# it (plus the reply) in the SAME WAL frame as the mutation, making
# exactly-once durable across its own restart — one frame, no window
# between "applied" and "reply cached" for a crash to split.
_current_replay_key: contextvars.ContextVar = contextvars.ContextVar(
    "rpc_replay_key", default=None)


def current_replay_key() -> Optional[Tuple[bytes, int, str]]:
    """(client_id, msg_id, method) of the in-flight replay-cached request,
    or None outside such a dispatch."""
    return _current_replay_key.get()

_LEN = struct.Struct("<I")
REQUEST, REPLY, ERROR, ONEWAY = 0, 1, 2, 3

MAX_FRAME = 512 * 1024 * 1024

# completed replies kept for duplicate/retried delivery replay, per server
REPLAY_CACHE_SIZE = 4096


class RpcError(Exception):
    pass


class RpcConnectionError(RpcError):
    pass


class RpcTimeoutError(RpcError):
    pass


class _ConnectionLostMidCall(RpcConnectionError):
    """Internal: an ESTABLISHED connection dropped before the reply. The only
    failure the transparent retry loop absorbs — a reconnect that fails means
    the peer is gone, and that must surface immediately (callers like the
    actor push path re-resolve a NEW address on RpcConnectionError; eating
    the signal here would starve their failover)."""


class RemoteError(RpcError):
    """An exception raised inside the remote handler, re-raised locally."""

    def __init__(self, method: str, cause_repr: str, cause: Exception | None = None):
        super().__init__(f"remote handler {method!r} failed: {cause_repr}")
        self.cause = cause


def replay_cached(fn):
    """Mark an ``rpc_*`` handler non-idempotent: the server caches its reply
    keyed by (client_id, msg_id) and replays it for duplicated or retried
    deliveries instead of re-executing. Use for anything that mints ids,
    grants resources, or appends durable records."""
    fn._rpc_replay_cached = True
    return fn


def idempotent(fn):
    """Audit marker: re-executing this handler with the same body converges
    to the same state (reads, overwrite-by-key writes, guarded transitions).
    Duplicated/retried deliveries may re-execute it freely."""
    fn._rpc_idempotent = True
    return fn


async def _read_frame(reader: asyncio.StreamReader) -> bytes:
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    return await reader.readexactly(length)


def _write_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(_LEN.pack(len(payload)))
    writer.write(payload)


class RpcServer:
    """Method-dispatch TCP server.

    Handlers are registered by name; they may be sync or async, and receive
    (body, ) or (body, peer) if they accept two arguments.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._handlers: Dict[str, Callable] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()
        # exactly-once layer for non-idempotent methods: (client_id, msg_id)
        # -> completed reply payload bytes, or an asyncio.Future while the
        # first delivery is still executing (concurrent duplicates await it)
        self._replay_methods: set = set()
        self._replay_cache: "OrderedDict[Tuple[bytes, int], Any]" = OrderedDict()

    def register(self, method: str, handler: Callable,
                 replay_cached: bool = False) -> None:
        if replay_cached or getattr(handler, "_rpc_replay_cached", False):
            self._replay_methods.add(method)
        self._handlers[method] = handler

    def register_object(self, obj: Any, prefix: str = "") -> None:
        """Register every public method of obj whose name starts with 'rpc_'."""
        for name in dir(obj):
            if name.startswith("rpc_"):
                self.register(prefix + name[4:], getattr(obj, name))

    @property
    def address(self) -> Tuple[str, int]:
        return (self._host, self._port)

    @property
    def address_str(self) -> str:
        return f"{self._host}:{self._port}"

    async def start(self) -> Tuple[str, int]:
        self._server = await asyncio.start_server(
            self._handle_conn, self._host, self._port, limit=MAX_FRAME
        )
        self._port = self._server.sockets[0].getsockname()[1]
        return (self._host, self._port)

    async def stop(self) -> None:
        # Close live connections BEFORE wait_closed(): since 3.12,
        # Server.wait_closed() waits for every connection handler to finish,
        # and our handlers sit in read loops until the peer (or we) close.
        if self._server is not None:
            self._server.close()
        for w in list(self._conns):
            try:
                w.close()
            except Exception:
                pass
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=2)
            except Exception:
                pass

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        peer = writer.get_extra_info("peername")
        self._conns.add(writer)
        try:
            while True:
                try:
                    frame = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    break
                # Dispatch without blocking the read loop so one slow handler
                # doesn't head-of-line-block the connection.
                asyncio.get_running_loop().create_task(
                    self._dispatch(frame, writer, peer)
                )
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, frame: bytes, writer: asyncio.StreamWriter, peer):
        msg = pickle.loads(frame)
        kind, msg_id, method, body = msg[:4]
        client_id = msg[4] if len(msg) > 4 else None
        drop_reply = False
        if kind == REQUEST:
            fc = fault_controller()
            if fc is not None:
                decision = fc.rpc("server", method)
                if decision is not None:
                    if decision.delay_s:
                        await asyncio.sleep(decision.delay_s)
                    # server-side drop = the reply is lost in transit: the
                    # handler runs (and its reply is cached), then the
                    # connection severs so the client retries and must be
                    # served from the replay cache
                    drop_reply = decision.drop

        handler = self._handlers.get(method)
        if handler is None:
            if kind == REQUEST:
                self._send_reply(
                    writer,
                    self._encode_reply(ERROR, msg_id, method,
                                       f"no such method: {method}"),
                    drop_reply)
            return

        if kind == REQUEST:
            _m_server_requests.inc(labels={"method": method})
        key = None
        if kind == REQUEST and client_id is not None \
                and method in self._replay_methods:
            key = (client_id, msg_id)
            hit = self._replay_cache.get(key)
            if hit is not None:
                if isinstance(hit, asyncio.Future):
                    payload = await hit  # first delivery still executing
                else:
                    payload = hit
                self._send_reply(writer, payload, drop_reply)
                return
            self._replay_cache[key] = asyncio.get_running_loop().create_future()

        payload = None
        token = None
        if key is not None:
            # replay-cached handlers may fold this key into their durable
            # mutation record (controller WAL) for restart-proof dedupe
            token = _current_replay_key.set((client_id, msg_id, method))
        try:
            sig_args = (body, peer) if _wants_peer(handler) else (body,)
            result = handler(*sig_args)
            if inspect.isawaitable(result):
                result = await result
            if kind == REQUEST:
                payload = self._encode_reply(REPLY, msg_id, method, result)
        except Exception as e:  # noqa: BLE001 — handler errors cross the wire
            logger.debug("handler %s raised", method, exc_info=True)
            if kind == REQUEST:
                payload = self._encode_reply(ERROR, msg_id, method, e)
        finally:
            if token is not None:
                _current_replay_key.reset(token)
        if key is not None:
            self._finish_replay(key, payload)
        if payload is not None:
            self._send_reply(writer, payload, drop_reply)

    def _encode_reply(self, kind: int, msg_id, method: str, body) -> bytes:
        try:
            return serialization.dumps((kind, msg_id, method, body))
        except Exception:
            # unpicklable result/exception: degrade to its repr
            return serialization.dumps((ERROR, msg_id, method, repr(body)))

    def seed_replay(self, client_id: bytes, msg_id: int, method: str,
                    reply_value: Any) -> None:
        """Install a COMPLETED reply for (client_id, msg_id) — recovery
        seeding from WAL frames that embedded their replay key. A
        PR-1-style retry straddling the server's restart is then answered
        from the cache exactly like a same-incarnation redelivery."""
        self.seed_replay_payload(
            (client_id, msg_id),
            self._encode_reply(REPLY, msg_id, method, reply_value))

    def seed_replay_payload(self, key: Tuple[bytes, int],
                            payload: bytes) -> None:
        """Install a pre-encoded reply payload (snapshot-carried entries)."""
        existing = self._replay_cache.get(key)
        if isinstance(existing, asyncio.Future):
            return  # a live dispatch owns this key; never clobber it
        self._replay_cache[key] = payload
        self._replay_cache.move_to_end(key)
        excess = len(self._replay_cache) - REPLAY_CACHE_SIZE
        if excess > 0:
            for k in [k for k, v in self._replay_cache.items()
                      if not isinstance(v, asyncio.Future)][:excess]:
                del self._replay_cache[k]

    def export_replay(self) -> List[Tuple[bytes, int, bytes]]:
        """Completed replay entries as (client_id, msg_id, payload) — the
        snapshot's carry so compaction (which sweeps the WAL frames that
        embedded them) does not reopen the exactly-once window."""
        return [(k[0], k[1], v) for k, v in self._replay_cache.items()
                if not isinstance(v, asyncio.Future)]

    def _finish_replay(self, key, payload: bytes) -> None:
        fut = self._replay_cache.get(key)
        self._replay_cache[key] = payload
        self._replay_cache.move_to_end(key)
        if isinstance(fut, asyncio.Future) and not fut.done():
            fut.set_result(payload)
        # trim oldest COMPLETED entries only: evicting an in-flight Future
        # would strand duplicate dispatches awaiting it and let a late
        # retry re-execute the non-idempotent handler
        excess = len(self._replay_cache) - REPLAY_CACHE_SIZE
        if excess > 0:
            for k in [k for k, v in self._replay_cache.items()
                      if not isinstance(v, asyncio.Future)][:excess]:
                del self._replay_cache[k]

    def _send_reply(self, writer, payload: bytes, drop_reply: bool) -> None:
        if drop_reply:
            # injected reply loss: sever so the client's retry machinery
            # (not a silent timeout) observes it
            try:
                writer.close()
            except Exception:
                pass
            return
        try:
            _write_frame(writer, payload)
        except (ConnectionResetError, RuntimeError):
            pass


def _wants_peer(handler) -> bool:
    try:
        params = inspect.signature(handler).parameters
        return len([p for p in params.values() if p.default is p.empty]) >= 2
    except (TypeError, ValueError):
        return False


class RpcClient:
    """Multiplexed client with lazy connect and transparent retry.

    A call whose connection drops before the reply arrives reconnects and
    resends the SAME (client_id, msg_id) with exponential backoff + jitter,
    all under a single deadline budget — the server's replay cache makes the
    resend exactly-once for non-idempotent methods. All calls must run on the
    owning event loop.
    """

    def __init__(
        self,
        address: Tuple[str, int] | str,
        connect_timeout_s: float = 10.0,
        request_timeout_s: float = 60.0,
        retry_base_s: float = 0.1,
    ):
        if isinstance(address, str):
            host, port = address.rsplit(":", 1)
            address = (host, int(port))
        self._addr = address
        self._connect_timeout = connect_timeout_s
        self._request_timeout = request_timeout_s
        self._retry_base = max(0.001, retry_base_s)
        self._client_id = os.urandom(8)
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._lock = asyncio.Lock()
        self._read_task: Optional[asyncio.Task] = None
        self._closed = False
        self._ever_connected = False
        # fired (as tasks) after a RE-connect — i.e. the peer process may
        # have restarted and lost its soft state. The controller-restart
        # protocol hangs off this: core workers re-subscribe their pubsub
        # channels here, event-driven, with zero steady-state polling.
        self._reconnect_hooks: List[Callable[[], Any]] = []
        self._eager_task: Optional[asyncio.Task] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._addr

    async def _ensure_connected(self, one_shot: bool = False) -> None:
        """Establish the connection. ``one_shot`` (reconnect attempts inside
        a call's transparent retry) tries exactly once: if the peer cannot be
        re-reached NOW it is presumed dead and the caller must fail over —
        only the initial connect gets the patient retry window (the peer may
        legitimately still be starting up)."""
        if self._writer is not None and not self._writer.is_closing():
            return
        async with self._lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            deadline = time.monotonic() + self._connect_timeout
            delay = 0.05
            while True:
                try:
                    self._reader, self._writer = await asyncio.wait_for(
                        asyncio.open_connection(*self._addr, limit=MAX_FRAME),
                        timeout=max(0.1, deadline - time.monotonic()),
                    )
                    sock = self._writer.get_extra_info("socket")
                    if sock is not None:
                        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    break
                except (OSError, asyncio.TimeoutError) as e:
                    if one_shot or time.monotonic() + delay >= deadline \
                            or self._closed:
                        raise RpcConnectionError(
                            f"cannot connect to {self._addr}: {e}"
                        ) from e
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, 1.0)
            loop = asyncio.get_running_loop()
            self._read_task = loop.create_task(self._read_loop())
            reconnected = self._ever_connected
            self._ever_connected = True
            if reconnected:
                # the peer was reachable before and the connection is
                # fresh: it may be a restarted incarnation with empty
                # soft state — let interested layers re-establish theirs
                # (idempotent re-subscribes; a mere TCP blip re-adds the
                # same set entries). Run as tasks: a hook that RPCs back
                # through this client must not re-enter under our lock.
                for hook in list(self._reconnect_hooks):
                    loop.create_task(self._run_reconnect_hook(hook))

    @staticmethod
    async def _run_reconnect_hook(hook: Callable[[], Any]) -> None:
        try:
            result = hook()
            if inspect.isawaitable(result):
                await result
        except Exception:
            logger.debug("reconnect hook failed", exc_info=True)

    def add_reconnect_hook(self, hook: Callable[[], Any]) -> None:
        """Register a callback (sync or async) fired after every
        re-established connection to this peer."""
        self._reconnect_hooks.append(hook)

    async def _read_loop(self) -> None:
        reader = self._reader
        try:
            while True:
                frame = await _read_frame(reader)
                kind, msg_id, method, body = pickle.loads(frame)[:4]
                fut = self._pending.pop(msg_id, None)
                if fut is None or fut.done():
                    continue
                if kind == REPLY:
                    fut.set_result(body)
                elif kind == ERROR:
                    if isinstance(body, Exception):
                        fut.set_exception(RemoteError(method, repr(body), body))
                    else:
                        fut.set_exception(RemoteError(method, str(body)))
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError, asyncio.CancelledError):
            pass
        finally:
            err = RpcConnectionError(f"connection to {self._addr} lost")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()
            if self._writer is not None:
                try:
                    self._writer.close()
                except Exception:
                    pass
            self._writer = None
            self._reader = None
            if self._reconnect_hooks and not self._closed \
                    and self._eager_task is None:
                # a hook-bearing client (a core worker watching the
                # controller) reconnects EAGERLY: an idle process — the
                # zero-RPC steady state — would otherwise never re-issue
                # its subscriptions after a controller restart and
                # silently miss actor/node death fan-out. One bounded
                # backoff loop per outage; nothing periodic at steady
                # state.
                try:
                    self._eager_task = asyncio.get_running_loop(
                    ).create_task(self._eager_reconnect())
                except RuntimeError:
                    pass

    async def _eager_reconnect(self) -> None:
        delay = 0.5
        try:
            while not self._closed:
                await asyncio.sleep(delay)
                if self._writer is not None \
                        and not self._writer.is_closing():
                    return  # a concurrent call already reconnected
                try:
                    # success fires the reconnect hooks from inside
                    await self._ensure_connected(one_shot=True)
                    return
                except RpcConnectionError:
                    delay = min(delay * 2, 5.0)
        finally:
            self._eager_task = None

    def reserve_msg_id(self) -> int:
        """Pre-allocate a request id so several call() attempts can share one
        (client_id, msg_id) replay-cache key (see retry_call)."""
        self._next_id += 1
        return self._next_id

    async def call(self, method: str, body: Any = None,
                   timeout: float | None = None,
                   _reuse_msg_id: int | None = None) -> Any:
        # One deadline covers connect + request + every transparent retry
        # (a 2s call must not ride a 10s connect-retry window to a dead
        # peer, nor get a fresh 2s after a 1.9s connect).
        budget = timeout if timeout is not None else self._request_timeout
        deadline = time.monotonic() + budget
        if _reuse_msg_id is not None:
            # a retry_call attempt riding a shared replay-cache key: the
            # logical call was already counted (by retry_call), and a
            # redelivery is not a new control decision
            msg_id = _reuse_msg_id
        else:
            _m_client_calls.inc(labels={"method": method})
            msg_id = self.reserve_msg_id()
        # the payload (same msg_id) is reused verbatim across retries so the
        # server-side replay cache can recognize the redelivery
        payload = serialization.dumps(
            (REQUEST, msg_id, method, body, self._client_id))
        attempt = 0
        while True:
            try:
                return await self._attempt(method, msg_id, payload, deadline,
                                           reconnect=attempt > 0)
            except _ConnectionLostMidCall:
                # the peer WAS reachable and the frame (or its reply) was
                # lost — retry under the deadline; a reconnect that fails
                # raises plain RpcConnectionError out of _attempt instead,
                # surfacing peer death to the caller's failover logic
                attempt += 1
                delay = min(self._retry_base * (2 ** (attempt - 1)), 2.0)
                delay *= 0.5 + random.random()  # jitter: 0.5x..1.5x
                if self._closed or time.monotonic() + delay >= deadline:
                    raise
                await asyncio.sleep(delay)

    async def _attempt(self, method: str, msg_id: int, payload: bytes,
                       deadline: float, reconnect: bool) -> Any:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise RpcConnectionError(
                f"{method} to {self._addr}: deadline exhausted reconnecting")
        try:
            # connect failures are TERMINAL for the call (peer unreachable);
            # one_shot on reconnects keeps dead-peer failover instant
            await asyncio.wait_for(self._ensure_connected(one_shot=reconnect),
                                   timeout=remaining)
        except asyncio.TimeoutError as e:
            raise RpcConnectionError(
                f"cannot connect to {self._addr} within budget") from e
        # snapshot: the read loop nulls self._writer when the connection
        # dies, and that can interleave even between _ensure_connected
        # resolving and this coroutine resuming — never deref the attribute
        # after an await
        writer = self._writer
        if writer is None:
            raise _ConnectionLostMidCall(
                f"connection to {self._addr} lost before send")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        try:
            send = True
            fc = fault_controller()
            if fc is not None:
                decision = fc.rpc("client", method)
                if decision is not None:
                    if decision.delay_s:
                        await asyncio.sleep(decision.delay_s)
                    if decision.drop:
                        # request lost in transit: sever instead of sending;
                        # the read loop fails `fut` and the retry loop in
                        # call() re-sends — exactly a real network drop
                        send = False
                        writer.close()
                    elif decision.duplicate:
                        _write_frame(writer, payload)
            if send:
                if writer.is_closing():
                    raise _ConnectionLostMidCall(
                        f"connection to {self._addr} closed before send")
                _write_frame(writer, payload)
                await writer.drain()
            return await asyncio.wait_for(
                fut, max(0.05, deadline - time.monotonic())
            )
        except asyncio.TimeoutError as e:
            raise RpcTimeoutError(f"{method} to {self._addr} timed out") from e
        except RpcConnectionError as e:
            # the established connection died before the reply (read loop
            # failed our future) — the one retriable failure
            raise _ConnectionLostMidCall(str(e)) from e
        except (ConnectionResetError, BrokenPipeError, OSError) as e:
            raise _ConnectionLostMidCall(
                f"send to {self._addr} failed: {e}") from e
        finally:
            # the read loop pops on reply; this covers every other exit —
            # drain/serialization failures, timeouts, cancellation — so a
            # failed attempt can never leak its pending-future entry
            self._pending.pop(msg_id, None)

    async def notify(self, method: str, body: Any = None) -> None:
        """Fire-and-forget (at-most-once; never retried)."""
        _m_client_calls.inc(labels={"method": method})
        await self._ensure_connected()
        writer = self._writer  # see _attempt: never deref after an await
        if writer is None:
            raise RpcConnectionError(
                f"connection to {self._addr} lost before send")
        self._next_id += 1
        payload = serialization.dumps(
            (ONEWAY, self._next_id, method, body, self._client_id))
        fc = fault_controller()
        if fc is not None:
            decision = fc.rpc("client", method)
            if decision is not None:
                if decision.delay_s:
                    await asyncio.sleep(decision.delay_s)
                if decision.drop:
                    return  # lost in transit (oneway: nothing notices)
                if decision.duplicate:
                    _write_frame(writer, payload)
        if writer.is_closing():
            raise RpcConnectionError(
                f"connection to {self._addr} closed before send")
        _write_frame(writer, payload)
        await writer.drain()

    async def close(self) -> None:
        self._closed = True
        if self._read_task is not None:
            self._read_task.cancel()
        if self._eager_task is not None:
            self._eager_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
        self._writer = None


async def retry_call(
    client: RpcClient,
    method: str,
    body: Any = None,
    *,
    timeout: float | None = None,
    per_call_timeout: float | None = None,
    base_interval_s: float = 0.1,
    max_interval_s: float = 5.0,
    retry_on: tuple = (RpcConnectionError, RpcTimeoutError),
) -> Any:
    """Deadline-budgeted retry wrapper shared by control-plane call sites.

    ``RpcClient.call`` already retries transparently on connection loss; this
    helper additionally absorbs peer restarts and per-call timeouts across a
    longer window — the replacement for the hand-rolled fixed-interval retry
    loops daemons used to carry. ``timeout`` bounds the WHOLE effort
    (defaults to the client's request timeout); each attempt gets
    ``per_call_timeout`` (clamped to the remaining budget); sleeps between
    attempts follow exponential backoff from ``base_interval_s``
    (``Config.rpc_retry_interval_ms`` at call sites) with 0.5x..1.5x jitter.
    Safe for non-idempotent methods only because the server's replay cache
    dedupes redeliveries — every attempt here shares ONE reserved
    (client_id, msg_id) key, so even a retry after a timeout whose first
    delivery actually executed is answered from the cache, never
    re-executed."""
    budget = timeout if timeout is not None else client._request_timeout
    deadline = time.monotonic() + budget
    msg_id = client.reserve_msg_id()
    # one logical call regardless of how many attempts share the msg_id
    _m_client_calls.inc(labels={"method": method})
    attempt = 0
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise RpcTimeoutError(
                f"{method} to {client.address}: retry budget exhausted")
        call_timeout = remaining if per_call_timeout is None \
            else min(per_call_timeout, remaining)
        try:
            return await client.call(method, body, timeout=call_timeout,
                                     _reuse_msg_id=msg_id)
        except retry_on:
            attempt += 1
            delay = min(base_interval_s * (2 ** (attempt - 1)), max_interval_s)
            delay *= 0.5 + random.random()
            if time.monotonic() + delay >= deadline:
                raise
            await asyncio.sleep(delay)


async def call_chunked(
    client: RpcClient,
    method: str,
    base_body: dict,
    payload,
    *,
    chunk_bytes: int,
    window: int,
    timeout: float,
) -> int:
    """Ship ``payload`` as a bounded window of ``method`` frames.

    The shared transfer shape of the data plane (object pulls, compiled-DAG
    mirror pushes, collective ring segments): each frame is
    ``{**base_body, "offset": <byte offset>, "data": <chunk>}``, at most
    ``window`` frames in flight at once, every frame under the caller's one
    deadline budget. Handlers must be idempotent (same-offset rewrites
    converge), which makes drop/dup/retry safe without a replay cache.
    A zero-length payload still sends one frame so the receiver observes
    the message. Returns the number of frames sent; any frame failure
    cancels the rest of the window and propagates."""
    view = memoryview(payload)
    chunk = max(1, int(chunk_bytes))
    offsets = list(range(0, len(view), chunk)) or [0]
    deadline = time.monotonic() + timeout
    sem = asyncio.Semaphore(max(1, int(window)))

    async def send(pos: int) -> None:
        async with sem:
            await client.call(
                method,
                {**base_body, "offset": pos,
                 "data": bytes(view[pos:pos + chunk])},
                timeout=max(0.05, deadline - time.monotonic()))

    tasks = [asyncio.ensure_future(send(pos)) for pos in offsets]
    try:
        await asyncio.gather(*tasks)
    except BaseException:
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        raise
    return len(offsets)


class ClientPool:
    """Cache of RpcClients keyed by address."""

    def __init__(self, connect_timeout_s: float = 10.0,
                 request_timeout_s: float = 60.0,
                 retry_base_s: float = 0.1):
        self._clients: Dict[Tuple[str, int], RpcClient] = {}
        self._connect_timeout = connect_timeout_s
        self._request_timeout = request_timeout_s
        self._retry_base = retry_base_s

    def get(self, address: Tuple[str, int] | str) -> RpcClient:
        if isinstance(address, str):
            host, port = address.rsplit(":", 1)
            address = (host, int(port))
        client = self._clients.get(address)
        if client is None:
            client = RpcClient(
                address,
                connect_timeout_s=self._connect_timeout,
                request_timeout_s=self._request_timeout,
                retry_base_s=self._retry_base,
            )
            self._clients[address] = client
        return client

    def drop(self, address: Tuple[str, int]) -> None:
        self._clients.pop(address, None)

    async def close_all(self) -> None:
        for c in self._clients.values():
            await c.close()
        self._clients.clear()
