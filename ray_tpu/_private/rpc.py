"""Async RPC substrate for the control plane.

TPU-native analog of the reference's gRPC wrapper layer (`src/ray/rpc/
grpc_server.h`, `grpc_client.h`, `client_call.h`): every daemon (controller,
supervisor, worker) runs one ``RpcServer``; peers hold multiplexed,
auto-reconnecting ``RpcClient``s.

We deliberately do not use gRPC for the control plane: the reference needs
gRPC for cross-language parity (C++/Java/Python all speak the same proto); our
control plane is Python+C++ only and latency-bound by asyncio scheduling, not
marshalling. The wire protocol is length-prefixed pickles over TCP — trivially
inspectable, no proto toolchain in the loop, and the object-payload path never
rides it (objects move via the shared-memory store and the chunked transfer
protocol in object_store.py / supervisor.py).

Frame: [u32 little-endian length][payload]
Payload: pickle of (kind, msg_id, method, body)
  kind: 0=request 1=reply 2=error 3=oneway
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import pickle
import socket
import struct
import time
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

from ray_tpu._private import serialization

logger = logging.getLogger(__name__)

_LEN = struct.Struct("<I")
REQUEST, REPLY, ERROR, ONEWAY = 0, 1, 2, 3

MAX_FRAME = 512 * 1024 * 1024


class RpcError(Exception):
    pass


class RpcConnectionError(RpcError):
    pass


class RpcTimeoutError(RpcError):
    pass


class RemoteError(RpcError):
    """An exception raised inside the remote handler, re-raised locally."""

    def __init__(self, method: str, cause_repr: str, cause: Exception | None = None):
        super().__init__(f"remote handler {method!r} failed: {cause_repr}")
        self.cause = cause


async def _read_frame(reader: asyncio.StreamReader) -> bytes:
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    return await reader.readexactly(length)


def _write_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(_LEN.pack(len(payload)))
    writer.write(payload)


class RpcServer:
    """Method-dispatch TCP server.

    Handlers are registered by name; they may be sync or async, and receive
    (body, ) or (body, peer) if they accept two arguments.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._handlers: Dict[str, Callable] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()

    def register(self, method: str, handler: Callable) -> None:
        self._handlers[method] = handler

    def register_object(self, obj: Any, prefix: str = "") -> None:
        """Register every public method of obj whose name starts with 'rpc_'."""
        for name in dir(obj):
            if name.startswith("rpc_"):
                self.register(prefix + name[4:], getattr(obj, name))

    @property
    def address(self) -> Tuple[str, int]:
        return (self._host, self._port)

    @property
    def address_str(self) -> str:
        return f"{self._host}:{self._port}"

    async def start(self) -> Tuple[str, int]:
        self._server = await asyncio.start_server(
            self._handle_conn, self._host, self._port, limit=MAX_FRAME
        )
        self._port = self._server.sockets[0].getsockname()[1]
        return (self._host, self._port)

    async def stop(self) -> None:
        # Close live connections BEFORE wait_closed(): since 3.12,
        # Server.wait_closed() waits for every connection handler to finish,
        # and our handlers sit in read loops until the peer (or we) close.
        if self._server is not None:
            self._server.close()
        for w in list(self._conns):
            try:
                w.close()
            except Exception:
                pass
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=2)
            except Exception:
                pass

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        peer = writer.get_extra_info("peername")
        self._conns.add(writer)
        try:
            while True:
                try:
                    frame = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    break
                # Dispatch without blocking the read loop so one slow handler
                # doesn't head-of-line-block the connection.
                asyncio.get_running_loop().create_task(
                    self._dispatch(frame, writer, peer)
                )
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, frame: bytes, writer: asyncio.StreamWriter, peer):
        kind, msg_id, method, body = pickle.loads(frame)
        handler = self._handlers.get(method)
        if handler is None:
            if kind == REQUEST:
                self._reply(writer, ERROR, msg_id, method, f"no such method: {method}")
            return
        try:
            sig_args = (body, peer) if _wants_peer(handler) else (body,)
            result = handler(*sig_args)
            if inspect.isawaitable(result):
                result = await result
            if kind == REQUEST:
                self._reply(writer, REPLY, msg_id, method, result)
        except Exception as e:  # noqa: BLE001 — handler errors cross the wire
            logger.debug("handler %s raised", method, exc_info=True)
            if kind == REQUEST:
                try:
                    self._reply(writer, ERROR, msg_id, method, e)
                except Exception:
                    self._reply(writer, ERROR, msg_id, method, repr(e))

    def _reply(self, writer, kind, msg_id, method, body):
        try:
            payload = serialization.dumps((kind, msg_id, method, body))
            _write_frame(writer, payload)
        except (ConnectionResetError, RuntimeError):
            pass


def _wants_peer(handler) -> bool:
    try:
        params = inspect.signature(handler).parameters
        return len([p for p in params.values() if p.default is p.empty]) >= 2
    except (TypeError, ValueError):
        return False


class RpcClient:
    """Multiplexed client with lazy connect and bounded reconnection.

    All calls must run on the owning event loop.
    """

    def __init__(
        self,
        address: Tuple[str, int] | str,
        connect_timeout_s: float = 10.0,
        request_timeout_s: float = 60.0,
    ):
        if isinstance(address, str):
            host, port = address.rsplit(":", 1)
            address = (host, int(port))
        self._addr = address
        self._connect_timeout = connect_timeout_s
        self._request_timeout = request_timeout_s
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._lock = asyncio.Lock()
        self._read_task: Optional[asyncio.Task] = None
        self._closed = False

    @property
    def address(self) -> Tuple[str, int]:
        return self._addr

    async def _ensure_connected(self) -> None:
        if self._writer is not None and not self._writer.is_closing():
            return
        async with self._lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            deadline = time.monotonic() + self._connect_timeout
            delay = 0.05
            while True:
                try:
                    self._reader, self._writer = await asyncio.wait_for(
                        asyncio.open_connection(*self._addr, limit=MAX_FRAME),
                        timeout=max(0.1, deadline - time.monotonic()),
                    )
                    sock = self._writer.get_extra_info("socket")
                    if sock is not None:
                        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    break
                except (OSError, asyncio.TimeoutError) as e:
                    if time.monotonic() + delay >= deadline or self._closed:
                        raise RpcConnectionError(
                            f"cannot connect to {self._addr}: {e}"
                        ) from e
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, 1.0)
            self._read_task = asyncio.get_running_loop().create_task(self._read_loop())

    async def _read_loop(self) -> None:
        reader = self._reader
        try:
            while True:
                frame = await _read_frame(reader)
                kind, msg_id, method, body = pickle.loads(frame)
                fut = self._pending.pop(msg_id, None)
                if fut is None or fut.done():
                    continue
                if kind == REPLY:
                    fut.set_result(body)
                elif kind == ERROR:
                    if isinstance(body, Exception):
                        fut.set_exception(RemoteError(method, repr(body), body))
                    else:
                        fut.set_exception(RemoteError(method, str(body)))
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError, asyncio.CancelledError):
            pass
        finally:
            err = RpcConnectionError(f"connection to {self._addr} lost")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()
            if self._writer is not None:
                try:
                    self._writer.close()
                except Exception:
                    pass
            self._writer = None
            self._reader = None

    async def call(self, method: str, body: Any = None, timeout: float | None = None) -> Any:
        # one deadline covers connect + request (a 2s call must not ride a
        # 10s connect-retry window to a dead peer, nor get a fresh 2s after
        # a 1.9s connect)
        budget = timeout if timeout is not None else self._request_timeout
        deadline = time.monotonic() + budget
        if timeout is not None:
            try:
                await asyncio.wait_for(self._ensure_connected(), timeout=budget)
            except asyncio.TimeoutError as e:
                raise RpcConnectionError(
                    f"cannot connect to {self._addr} within {timeout}s"
                ) from e
        else:
            await self._ensure_connected()
        self._next_id += 1
        msg_id = self._next_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        _write_frame(self._writer, serialization.dumps((REQUEST, msg_id, method, body)))
        try:
            await self._writer.drain()
            return await asyncio.wait_for(
                fut, max(0.05, deadline - time.monotonic())
            )
        except asyncio.TimeoutError as e:
            self._pending.pop(msg_id, None)
            raise RpcTimeoutError(f"{method} to {self._addr} timed out") from e

    async def notify(self, method: str, body: Any = None) -> None:
        """Fire-and-forget."""
        await self._ensure_connected()
        self._next_id += 1
        _write_frame(
            self._writer, serialization.dumps((ONEWAY, self._next_id, method, body))
        )
        await self._writer.drain()

    async def close(self) -> None:
        self._closed = True
        if self._read_task is not None:
            self._read_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
        self._writer = None


class ClientPool:
    """Cache of RpcClients keyed by address."""

    def __init__(self, connect_timeout_s: float = 10.0, request_timeout_s: float = 60.0):
        self._clients: Dict[Tuple[str, int], RpcClient] = {}
        self._connect_timeout = connect_timeout_s
        self._request_timeout = request_timeout_s

    def get(self, address: Tuple[str, int] | str) -> RpcClient:
        if isinstance(address, str):
            host, port = address.rsplit(":", 1)
            address = (host, int(port))
        client = self._clients.get(address)
        if client is None:
            client = RpcClient(
                address,
                connect_timeout_s=self._connect_timeout,
                request_timeout_s=self._request_timeout,
            )
            self._clients[address] = client
        return client

    def drop(self, address: Tuple[str, int]) -> None:
        self._clients.pop(address, None)

    async def close_all(self) -> None:
        for c in self._clients.values():
            await c.close()
        self._clients.clear()
