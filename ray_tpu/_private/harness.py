"""Shared wedge-proofing for the official harnesses (bench.py and
`__graft_entry__.dryrun_multichip`).

Round-3/4 postmortem: a SIGKILLed driver's orphan daemons held the
single-client TPU tunnel and every later backend init blocked forever;
with `PALLAS_AXON_REMOTE_COMPILE=1` even CPU-platform work routes at the
tunnel. Both harnesses therefore (a) keep the parent jax-free, (b) sweep
stale daemons first (`reaper.reap_all`), and (c) run all jax work in a
killable process-group child via `run_killable`. This module is the one
place those mechanics live so a future fix lands everywhere at once.

Reference analog for the recovery stance: raylet suicide on client
disconnect (`src/ray/raylet/node_manager.cc:1432`) and GCS health checks
(`src/ray/gcs/gcs_server/gcs_health_check_manager.h:39`).
"""

from __future__ import annotations

import os
import signal
import subprocess
from typing import Dict, List, Optional, Tuple


def scrub_axon_cpu(env: Optional[Dict[str, str]] = None,
                   n_devices: Optional[int] = None) -> Dict[str, str]:
    """Child env guaranteed off any TPU tunnel: CPU-only platform, axon
    routing disabled. With *n_devices*, also virtualize that many host
    devices (the driver's own multichip recipe)."""
    env = dict(os.environ if env is None else env)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
    if n_devices:
        flags = " ".join(
            f for f in env.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count"))
        env["XLA_FLAGS"] = (
            flags +
            f" --xla_force_host_platform_device_count={n_devices}").strip()
    return env


def run_killable(argv: List[str], *, env: Optional[Dict[str, str]] = None,
                 timeout: float, cwd: Optional[str] = None,
                 capture_stderr: bool = True,
                 ) -> Tuple[Optional[int], str, str, bool]:
    """Run *argv* in its own session; SIGKILL the whole group on timeout.

    Returns ``(returncode, stdout, stderr, timed_out)``. Output flushed
    by the child before a timeout kill is still collected (the salvage
    path bench.py relies on: the primary record is emitted early exactly
    so a wedge in an optional later phase can't discard it).
    """
    proc = subprocess.Popen(
        argv, env=env, cwd=cwd, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE if capture_stderr else None, text=True,
        start_new_session=True)  # killable with any tpu helper procs
    timed_out = False
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        timed_out = True
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            proc.kill()
        out, err = proc.communicate()
    return proc.returncode, out or "", err or "", timed_out


def tpu_probe(timeout: float = 180.0, log=None) -> bool:
    """Can a fresh process reach the TPU backend? A killable child runs
    a tiny device program; a wedged tunnel (jax init blocking forever —
    the round-4/5 outage mode) times out and is SIGKILLed instead of
    consuming a full benchmark attempt's budget."""
    import sys

    rc, out, _err, timed_out = run_killable(
        [sys.executable, "-c",
         "import jax, jax.numpy as jnp; "
         "x = jnp.ones((64, 64)); print('PROBE-OK', float((x @ x)[0, 0]))"],
        timeout=timeout)
    ok = rc == 0 and "PROBE-OK" in out
    if log is not None:
        log(f"tpu probe {'ok' if ok else 'FAILED'} "
            f"(rc={rc}, timed_out={timed_out})")
    return ok


def preflight_sweep(log) -> None:
    """Reap stale daemons/arenas; never let the sweep itself fail a run."""
    try:
        from ray_tpu._private.reaper import reap_all

        swept = reap_all()
        if any(swept.values()):
            log(f"pre-flight sweep {swept}")
    except Exception as e:
        log(f"reaper failed ({e!r}); continuing")
