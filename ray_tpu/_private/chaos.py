"""Deterministic, seed-driven fault injection for the control plane.

The reference runtime earns its fault tolerance from gRPC's retriable,
idempotent control RPCs; ours comes from the reconnect/retry + idempotency
layer in ``rpc.py``. This module is how we *prove* it: a ``FaultController``
that both sides of the RPC layer (and interested daemons) consult before
sending/handling a frame, injecting message drops (connection sever),
duplicated sends, bounded delays, and daemon crash points.

Determinism: every decision is a pure function of ``(seed, point, n)`` where
``point`` is a stable string like ``"client:request_lease"`` and ``n`` is the
per-point call counter — NOT a draw from one shared RNG stream. Concurrency
can reorder *which call* observes the n-th decision of a point, but the
decision sequence per point is byte-identical for a given seed, so a failing
seed replays the same fault schedule (``tests/test_chaos.py`` asserts this).

Configuration rides the normal ``Config``/env path (``RAY_TPU_CHAOS_*``), so
``Cluster(config=Config(chaos_seed=..., ...))`` propagates one schedule to
every daemon it spawns. All knobs default off; with ``chaos_seed < 0`` the
hot-path cost is one module-global ``is None`` check.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import os
import struct
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

_U64 = struct.Struct("<QQQQ")
_DENOM = float(1 << 64)

NO_FAULT = None  # sentinel meaning "no decision drawn / nothing to inject"


@dataclasses.dataclass(frozen=True)
class FaultDecision:
    """One injected fault for one RPC event.

    ``drop``      — lose the message in transit: the injector severs the
                    connection instead of delivering (client side: the request
                    never goes out; server side: the reply never comes back).
    ``duplicate`` — deliver the frame twice (client side only: two identical
                    request frames hit the server, exercising the dedupe
                    cache).
    ``delay_s``   — hold the frame this long before delivering.
    """

    drop: bool = False
    duplicate: bool = False
    delay_s: float = 0.0

    def any(self) -> bool:
        return self.drop or self.duplicate or self.delay_s > 0.0


class FaultController:
    """Seed-keyed fault schedule shared by client and server RPC paths.

    ``methods`` restricts injection to a comma-separated set of RPC method
    names ("" = every method). ``crash_points`` is
    ``"name[:nth][,name2[:nth]]"``: the nth time a daemon passes
    ``maybe_crash(name)`` the process hard-exits (SIGKILL analog), giving
    deterministic process-death placement inside a seeded run.
    """

    def __init__(
        self,
        seed: int,
        drop_prob: float = 0.0,
        dup_prob: float = 0.0,
        delay_prob: float = 0.0,
        delay_max_ms: int = 50,
        methods: str = "",
        crash_points: str = "",
        record: bool = False,
        exit_fn: Callable[[int], None] = os._exit,
    ):
        self.seed = int(seed)
        self.drop_prob = float(drop_prob)
        self.dup_prob = float(dup_prob)
        self.delay_prob = float(delay_prob)
        self.delay_max_ms = int(delay_max_ms)
        self._methods = frozenset(
            m.strip() for m in methods.split(",") if m.strip())
        self._counts: Dict[str, int] = {}
        self._crash_spec: Dict[str, int] = {}
        self._crash_hits: Dict[str, int] = {}
        self._exit_fn = exit_fn
        for part in (crash_points or "").split(","):
            part = part.strip()
            if not part:
                continue
            name, _, nth = part.partition(":")
            self._crash_spec[name] = int(nth) if nth else 1
        # optional schedule trace for the determinism test / seed bisection
        self.trace: Optional[List[Tuple[str, int, FaultDecision]]] = (
            [] if record else None)

    @classmethod
    def from_config(cls, cfg) -> Optional["FaultController"]:
        if getattr(cfg, "chaos_seed", -1) < 0:
            return None
        return cls(
            seed=cfg.chaos_seed,
            drop_prob=cfg.chaos_drop_prob,
            dup_prob=cfg.chaos_dup_prob,
            delay_prob=cfg.chaos_delay_prob,
            delay_max_ms=cfg.chaos_delay_max_ms,
            methods=cfg.chaos_methods,
            crash_points=cfg.chaos_crash_points,
        )

    # -------------------------------------------------------------- decisions

    def _uniforms(self, point: str, n: int) -> Tuple[float, float, float, float]:
        """Four U[0,1) values as a pure function of (seed, point, n)."""
        digest = hashlib.blake2b(
            f"{self.seed}:{point}:{n}".encode(), digest_size=32).digest()
        return tuple(v / _DENOM for v in _U64.unpack(digest))  # type: ignore[return-value]

    def rpc(self, side: str, method: str) -> Optional[FaultDecision]:
        """Decision for one RPC event. ``side`` is "client" (request about to
        be sent) or "server" (request received / reply about to be sent).
        Returns None when nothing is injected for this event."""
        if self._methods and method not in self._methods:
            return NO_FAULT
        point = f"{side}:{method}"
        n = self._counts.get(point, 0)
        self._counts[point] = n + 1
        u_drop, u_dup, u_delay, u_amount = self._uniforms(point, n)
        drop = u_drop < self.drop_prob
        decision = FaultDecision(
            drop=drop,
            # a dropped frame can't also be duplicated
            duplicate=(not drop) and u_dup < self.dup_prob,
            delay_s=(u_amount * self.delay_max_ms / 1000.0
                     if u_delay < self.delay_prob else 0.0),
        )
        if self.trace is not None:
            self.trace.append((point, n, decision))
        if not decision.any():
            return NO_FAULT
        return decision

    def local_delay(self, point: str) -> float:
        """Deterministic delay (seconds, possibly 0) for a non-RPC point —
        shared-memory protocols (compiled-graph channels) have no frame to
        drop or duplicate, but their seqlock timing can be perturbed: a
        delay between "reader observed version" and "reader acked" is
        exactly the interleaving a torn protocol would lose data under.
        Drawn from the same (seed, point, n) stream as rpc decisions, so a
        failing seed replays byte-identically."""
        if self._methods and point not in self._methods:
            return 0.0
        key = f"local:{point}"
        n = self._counts.get(key, 0)
        self._counts[key] = n + 1
        _, _, u_delay, u_amount = self._uniforms(key, n)
        delay = (u_amount * self.delay_max_ms / 1000.0
                 if u_delay < self.delay_prob else 0.0)
        if self.trace is not None:
            self.trace.append((key, n, FaultDecision(delay_s=delay)))
        return delay

    def schedule_bytes(self) -> bytes:
        """Canonical encoding of every decision drawn so far (record=True
        only) — the byte-identical replay artifact the determinism test
        compares."""
        if self.trace is None:
            raise RuntimeError("FaultController(record=True) required")
        out = []
        for point, n, d in self.trace:
            out.append(
                f"{point}#{n}:drop={int(d.drop)},dup={int(d.duplicate)},"
                f"delay_us={int(d.delay_s * 1e6)}")
        return "\n".join(out).encode()

    # ----------------------------------------------------------- crash points

    def maybe_crash(self, point: str) -> None:
        """Hard-exit the process the nth time this point is passed (only if
        the point was named in ``chaos_crash_points``)."""
        nth = self._crash_spec.get(point)
        if nth is None:
            return
        hits = self._crash_hits.get(point, 0) + 1
        self._crash_hits[point] = hits
        if hits == nth:
            logger.warning("chaos crash point %r hit %d: exiting", point, nth)
            self._exit_fn(137)


# ------------------------------------------------------------ process global

_controller: Optional[FaultController] = None
_configured = False


def fault_controller() -> Optional[FaultController]:
    """The process-wide controller, lazily built from the global Config
    (env-driven, so daemons spawned with RAY_TPU_CHAOS_* inherit the
    schedule). None — the overwhelmingly common case — means chaos is off."""
    global _controller, _configured
    if not _configured:
        from ray_tpu._private.config import global_config

        _controller = FaultController.from_config(global_config())
        _configured = True
    return _controller


def set_fault_controller(fc: Optional[FaultController]) -> None:
    """Install an explicit controller (tests)."""
    global _controller, _configured
    _controller = fc
    _configured = True


def reset() -> None:
    """Forget the cached controller; the next use re-reads config/env."""
    global _controller, _configured
    _controller = None
    _configured = False


def maybe_crash(point: str) -> None:
    """Convenience for daemon code: crash-point check against the process
    controller (no-op when chaos is off)."""
    fc = fault_controller()
    if fc is not None:
        fc.maybe_crash(point)


def maybe_delay(point: str) -> None:
    """Synchronous deterministic delay at a named local point (channel
    read/write/ack interleaving; no-op when chaos is off). Sync because
    the channel protocol runs on executor/user threads, never on an
    event loop."""
    fc = fault_controller()
    if fc is not None:
        delay = fc.local_delay(point)
        if delay > 0.0:
            import time

            time.sleep(delay)
