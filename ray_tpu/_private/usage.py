"""Usage telemetry (ref `python/ray/_private/usage/usage_lib.py`).

Records which libraries/features a cluster actually exercises plus
coarse cluster shape, and writes one JSON report under the session dir
at shutdown (`usage_report.json`). Reporting to a collector URL is
OPT-IN via RAY_TPU_USAGE_REPORT_URL (the reference reports by default
and offers RAY_USAGE_STATS_ENABLED=0; a TPU-first framework runs in
zero-egress pods, so the polarity flips to off-by-default). Disable
recording entirely with RAY_TPU_USAGE_STATS_ENABLED=0."""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Set

_lock = threading.Lock()
_libraries: Set[str] = set()
_features: Set[str] = set()
_started = time.time()


def enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") != "0"


def record_library_usage(name: str) -> None:
    """Called from library __init__ (train/tune/serve/data/rllib/...)."""
    if enabled():
        with _lock:
            _libraries.add(name)


def record_feature_usage(name: str) -> None:
    """Finer-grained feature tags (e.g. 'streaming_generator',
    'device_objects', 'pipeline_1f1b')."""
    if enabled():
        with _lock:
            _features.add(name)


def _cluster_shape() -> Dict[str, Any]:
    try:
        import ray_tpu

        if not ray_tpu.is_initialized():
            return {}
        nodes = ray_tpu.nodes()
        total = ray_tpu.cluster_resources()
        return {"num_nodes": len(nodes),
                "total_cpus": total.get("CPU"),
                "total_tpus": total.get("TPU")}
    except Exception:
        return {}


def build_report() -> Dict[str, Any]:
    import platform
    import sys

    with _lock:
        libs, feats = sorted(_libraries), sorted(_features)
    return {
        "schema_version": 1,
        "session_duration_s": round(time.time() - _started, 1),
        "python": sys.version.split()[0],
        "platform": platform.platform(),
        "libraries_used": libs,
        "features_used": feats,
        "cluster": _cluster_shape(),
    }


def write_report(session_dir: str) -> str:
    """Persist the report locally; POST it only when a collector URL is
    configured. Called from shutdown; must never raise."""
    path = os.path.join(session_dir, "usage_report.json")
    try:
        report = build_report()
        with open(path, "w") as f:
            json.dump(report, f, indent=2)
        url = os.environ.get("RAY_TPU_USAGE_REPORT_URL", "")
        if url:
            import urllib.request

            req = urllib.request.Request(
                url, data=json.dumps(report).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=5).read()
    except Exception:
        pass
    return path
