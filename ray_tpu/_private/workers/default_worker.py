"""Worker process main + task executor.

Analog of the reference's worker entrypoint
(`python/ray/_private/workers/default_worker.py`) plus the executor half of
CoreWorker (`CoreWorker::ExecuteTask` `core_worker.cc:2852`, scheduling queues
`transport/actor_scheduling_queue.h`): a worker registers with its
supervisor, then serves ``push_task`` RPCs.

Execution model:
  * normal tasks: FIFO on a single executor thread;
  * actor tasks: per-caller-handle sequence numbers enforce submission order
    when ``max_concurrency == 1`` (≈ ActorSchedulingQueue); threaded actors
    (`max_concurrency > 1`) run on a thread pool in arrival order
    (≈ out_of_order_actor_scheduling_queue.h + concurrency groups);
  * async actors: methods that are coroutines run on a dedicated asyncio loop
    with a ``max_concurrency`` semaphore (≈ fiber.h's fibers).

TPU specifics: before the first TPU task runs, the worker pins itself to its
assigned chips via ``TPU_VISIBLE_CHIPS`` (reference accelerators/tpu.py:30) —
jax then initializes only those chips when user code first touches it.
"""

from __future__ import annotations

import argparse
import asyncio
import inspect
import logging
import os
import threading
import traceback
from collections import OrderedDict, deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

from ray_tpu._private import serialization
from ray_tpu._private.config import Config
from ray_tpu._private.core_worker import CoreWorker, _RefPlaceholder
from ray_tpu._private.exceptions import TaskError
from ray_tpu._private.ids import JobID, ObjectID, TaskID, WorkerID
from ray_tpu._private.task_spec import ArgKind, TaskKind, TaskSpec

logger = logging.getLogger(__name__)


class Executor:
    """Executes task specs pushed to this worker."""

    def __init__(self, core: CoreWorker):
        self.core = core
        self.actor_instance: Any = None
        self.actor_spec: Optional[TaskSpec] = None
        self._pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="exec")
        self._async_loop: Optional[asyncio.AbstractEventLoop] = None
        self._async_sem: Optional[asyncio.Semaphore] = None
        # per-caller ordering state for sync actors
        self._expected_seq: Dict[str, int] = {}
        self._waiting: Dict[str, Dict[int, TaskSpec]] = {}
        self._cancelled: set = set()
        # push dedupe: the owner's push RPC may time out AFTER delivery
        # and retry elsewhere/again — a task id must execute at most once
        # here (bounded LRU)
        self._seen_pushes: "OrderedDict[TaskID, bool]" = OrderedDict()
        # streaming: last consumption watermark the owner told us, per task
        self._stream_consumed: Dict[TaskID, int] = {}
        # completion-report outbox (batched reply path, see _send_done);
        # appended from executor threads, drained on the IO loop (deque
        # append/popleft are thread-safe)
        self._done_outbox: deque = deque()
        self._done_flushing = False
        self._tpu_env_set = False
        self._lock = threading.Lock()

    # -- entry from the IO loop (RPC handler) --

    async def push_task(self, body) -> str:
        spec: TaskSpec = serialization.loads(body["spec"])
        if spec.task_id in self._seen_pushes:
            return "ok"  # duplicate delivery (timed-out push retried)
        self._seen_pushes[spec.task_id] = True
        while len(self._seen_pushes) > 10_000:
            self._seen_pushes.popitem(last=False)
        if spec.kind == TaskKind.ACTOR_CREATION and spec.max_concurrency > 1:
            # threaded actor: widen the execution pool before __init__ runs
            self._pool = ThreadPoolExecutor(
                max_workers=spec.max_concurrency, thread_name_prefix="exec"
            )
        if spec.kind == TaskKind.ACTOR_TASK and self.actor_spec is not None:
            if self.actor_spec.max_concurrency <= 1 and not self.actor_spec.is_async_actor:
                self._enqueue_ordered(spec)
                return "ok"
        if (
            spec.kind == TaskKind.ACTOR_TASK
            and self.actor_spec is not None
            and self.actor_spec.is_async_actor
        ):
            from ray_tpu._private.channels import CHANNEL_LOOP_METHOD

            if spec.method_name == CHANNEL_LOOP_METHOD:
                # the compiled-graph run loop is synchronous and
                # long-lived: parking it on the async actor's event loop
                # would starve every concurrent method and health ping —
                # run it on the thread pool like a sync task
                self._pool.submit(self._execute_guarded, spec)
                return "ok"
            self._submit_async(spec)
            return "ok"
        self._pool.submit(self._execute_guarded, spec)
        return "ok"

    async def push_task_batch(self, body) -> str:
        """Coalesced delivery: one frame, many specs (owner-side outbox
        batching). Each spec takes the exact same path as a single push —
        ordering still comes from seqnos, dedupe from task ids."""
        for blob in body["specs"]:
            await self.push_task({"spec": blob})
        return "ok"

    async def cancel(self, body) -> bool:
        self._cancelled.add(TaskID(body["task_id"]))
        return True

    def _enqueue_ordered(self, spec: TaskSpec) -> None:
        caller = getattr(spec, "caller_id", "") or "_"
        with self._lock:
            waiting = self._waiting.setdefault(caller, {})
            waiting[spec.seqno] = spec
            expected = self._expected_seq.get(caller, 0)
            while expected in waiting:
                ready = waiting.pop(expected)
                expected += 1
                self._pool.submit(self._execute_guarded, ready)
            self._expected_seq[caller] = expected

    def _submit_async(self, spec: TaskSpec) -> None:
        if self._async_loop is None:
            self._async_loop = asyncio.new_event_loop()
            t = threading.Thread(
                target=self._async_loop.run_forever, name="actor-async", daemon=True
            )
            t.start()
            conc = self.actor_spec.max_concurrency if self.actor_spec else 1
            self._async_sem = asyncio.Semaphore(max(1, conc))

        async def run():
            async with self._async_sem:
                await self._execute_async(spec)

        asyncio.run_coroutine_threadsafe(run(), self._async_loop)

    # -- execution --

    def _execute_guarded(self, spec: TaskSpec) -> None:
        try:
            self._execute(spec)
        except BaseException:
            logger.exception("executor crashed on %s", spec.name)

    def _resolve_args(self, spec: TaskSpec):
        value_arg = spec.args[0]
        plain_args, kwargs = serialization.unpack(value_arg.value)
        ref_args = spec.args[1:]
        if ref_args:
            from ray_tpu._private.api import ObjectRef

            refs = [
                ObjectRef(a.object_id, tuple(a.owner), skip_ref_counting=True)
                for a in ref_args
            ]
            values = self.core.get(refs)
            # placeholder.index is the 0-based REF-arg order from build_args
            plain_args = [
                values[a.index] if isinstance(a, _RefPlaceholder) else a
                for a in plain_args
            ]
        return plain_args, kwargs

    def _maybe_setup_tpu(self, spec: TaskSpec) -> None:
        if self._tpu_env_set or spec.required_resources().get("TPU", 0) <= 0:
            return
        try:
            chips = self.core._run(
                self.core.clients.get(self.core.supervisor_addr).call(
                    "tpu_visible_chips", {"worker_id_hex": self.core.worker_id.hex()}
                )
            )
            if chips and "TPU_VISIBLE_CHIPS" not in os.environ:
                os.environ["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in chips)
        except Exception:
            pass
        self._tpu_env_set = True

    def _get_callable(self, spec: TaskSpec):
        if spec.kind == TaskKind.ACTOR_TASK:
            if self.actor_instance is None:
                raise RuntimeError("actor task before actor creation")
            from ray_tpu._private import channels

            if spec.method_name == channels.CHANNEL_LOOP_METHOD:
                # compiled-graph execution: the "method" IS the per-actor
                # run loop (read input channels -> run stage methods ->
                # write output channels); it occupies this slot until the
                # graph is torn down or a participant dies
                import functools

                return functools.partial(
                    channels.run_actor_loop, self.core,
                    self.actor_instance)
            return getattr(self.actor_instance, spec.method_name)
        return self.core.get_function(spec.function_key)

    def _execute(self, spec: TaskSpec) -> None:
        from ray_tpu._private import chaos

        chaos.maybe_crash("worker.execute")
        if spec.task_id in self._cancelled:
            from ray_tpu._private.exceptions import TaskCancelledError

            self._report_error(spec, TaskCancelledError(spec.name), retryable=False)
            return
        self._maybe_setup_tpu(spec)
        try:
            args, kwargs = self._resolve_args(spec)
            fn = self._get_callable(spec)
            if spec.kind == TaskKind.ACTOR_CREATION:
                cls = fn
                self.actor_instance = cls(*args, **kwargs)
                self.actor_spec = spec
                self.core.actor_id = spec.actor_id
                self.core._run(self._notify_actor_ready(spec))
                self._report_results(spec, [None])
                return
            with self._task_span(spec):
                result = fn(*args, **kwargs)
                # inspect.iscoroutine, NOT asyncio.iscoroutine: on 3.10
                # the latter also matches plain generators (legacy
                # @asyncio.coroutine support), sending every sync
                # streaming task into run_until_complete -> "Task got
                # bad yield"
                if inspect.iscoroutine(result):
                    # sync path hit an async def: run it to completion here
                    # (loop closed afterwards — each leaks an epoll fd +
                    # self-pipe otherwise, EMFILE on long-lived workers)
                    _loop = asyncio.new_event_loop()
                    try:
                        result = _loop.run_until_complete(result)
                    finally:
                        _loop.close()
                if spec.is_streaming:
                    self._run_generator(spec, result)
                    return
            results = self._split_returns(spec, result)
            self._report_results(spec, results)
        except Exception as e:  # noqa: BLE001 — user exception crosses to owner
            err = TaskError.from_exception(spec.name, e)
            retryable = spec.retry_exceptions
            if spec.kind == TaskKind.ACTOR_CREATION:
                self.core._run(self._notify_creation_failed(spec, err))
                retryable = False
            self._report_error(spec, err, retryable)

    async def _execute_async(self, spec: TaskSpec) -> None:
        try:
            args, kwargs = await asyncio.get_running_loop().run_in_executor(
                None, self._resolve_args, spec
            )
            fn = self._get_callable(spec)
            with self._task_span(spec):
                result = fn(*args, **kwargs)
                # inspect (strict), not asyncio: see _execute — a plain
                # generator must reach the streaming path, not `await`
                if inspect.iscoroutine(result):
                    result = await result
                if spec.is_streaming:
                    await self._run_async_generator(spec, result)
                    return
            results = self._split_returns(spec, result)
            self._report_results(spec, results)
        except Exception as e:  # noqa: BLE001
            self._report_error(spec, TaskError.from_exception(spec.name, e), False)

    @staticmethod
    def _task_span(spec: TaskSpec):
        """Child span continuing the caller's propagated trace context
        (no-op nullcontext for untraced tasks)."""
        import contextlib

        if not spec.trace_ctx:
            return contextlib.nullcontext()
        from ray_tpu.util import tracing

        kind = "actor" if spec.actor_id is not None else "task"
        return tracing.remote_span(f"{kind}::{spec.name}", spec.trace_ctx)

    def _split_returns(self, spec: TaskSpec, result) -> list:
        if spec.num_returns == 1:
            return [result]
        if not isinstance(result, (tuple, list)) or len(result) != spec.num_returns:
            raise ValueError(
                f"task {spec.name} declared num_returns={spec.num_returns} but "
                f"returned {type(result).__name__}"
            )
        return list(result)

    # -- streaming generator tasks (num_returns="streaming") --

    def _run_generator(self, spec: TaskSpec, gen) -> None:
        """Drive a sync generator, reporting each yielded item to the
        owner as it is produced (≈ executor-side item reporting,
        core_worker.cc:3260). Item ids are deterministic
        (task_id + yield index) so a retried execution after a worker
        death replays onto the same ids."""
        if hasattr(gen, "__anext__"):
            # async generator reached the sync executor (e.g. a task
            # function defined async): drive it on a private loop
            _loop = asyncio.new_event_loop()
            try:
                _loop.run_until_complete(
                    self._run_async_generator(spec, gen))
            finally:
                _loop.close()
            return
        if not hasattr(gen, "__next__"):
            raise TypeError(
                f"task {spec.name} declared num_returns='streaming' but "
                f"returned {type(gen).__name__}, not a generator")
        from ray_tpu._private.exceptions import TaskCancelledError

        index = 0
        any_shared = False
        try:
            for item in gen:
                if spec.task_id in self._cancelled:
                    self._report_error(
                        spec, TaskCancelledError(spec.name), retryable=False)
                    return
                any_shared |= self._report_stream_item(spec, index, item)
                index += 1
                self._stream_backpressure(spec, index)
        except Exception as e:  # noqa: BLE001 — user generator raised
            self._report_error(spec, TaskError.from_exception(spec.name, e),
                               spec.retry_exceptions)
            return
        finally:
            self._stream_cleanup(spec)
        self._send_done(spec, {
            "task_id": spec.task_id.binary(), "results": [],
            "stream_count": index, "stream_any_shared": any_shared})

    def _stream_cleanup(self, spec: TaskSpec) -> None:
        """Per-stream executor state must not outlive the stream — a
        long-lived replica serves millions of them (the adjacent
        _seen_pushes cache is bounded for the same reason)."""
        self._stream_consumed.pop(spec.task_id, None)
        self._cancelled.discard(spec.task_id)

    async def _run_async_generator(self, spec: TaskSpec, agen) -> None:
        """Async-actor variant: drive an async generator on the actor's
        event loop (items interleave with other concurrent methods)."""
        if not hasattr(agen, "__anext__"):
            # plain generator from an async actor: drive it OFF the actor
            # loop — per-item report RPCs and backpressure sleeps would
            # otherwise stall every concurrent method and health ping
            await asyncio.get_running_loop().run_in_executor(
                None, self._run_generator, spec, agen)
            return
        from ray_tpu._private.exceptions import TaskCancelledError

        index = 0
        any_shared = False
        loop = asyncio.get_running_loop()
        try:
            async for item in agen:
                if spec.task_id in self._cancelled:
                    self._report_error(
                        spec, TaskCancelledError(spec.name), retryable=False)
                    return
                any_shared |= await loop.run_in_executor(
                    None, self._report_stream_item, spec, index, item)
                index += 1
                await loop.run_in_executor(
                    None, self._stream_backpressure, spec, index)
        except Exception as e:  # noqa: BLE001
            self._report_error(spec, TaskError.from_exception(spec.name, e),
                               spec.retry_exceptions)
            return
        finally:
            self._stream_cleanup(spec)
        self._send_done(spec, {
            "task_id": spec.task_id.binary(), "results": [],
            "stream_count": index, "stream_any_shared": any_shared})

    def _report_stream_item(self, spec: TaskSpec, index: int, item) -> bool:
        """Ship one yielded item to the owner; returns True if it went to
        the shared arena (size-routed exactly like normal returns)."""
        oid = ObjectID.for_task_return(spec.task_id, index)
        packed = serialization.pack(item)
        body = {"task_id": spec.task_id.binary(), "index": index,
                "object_id": oid.binary()}
        shared = len(packed) > self.core.config.max_direct_call_object_size
        if shared:
            self.core._run(self._store_shared(oid, packed))
            body["kind"] = "shared"
            body["payload"] = {"size": len(packed),
                               "node_addr": self.core.supervisor_addr}
        else:
            body["kind"] = "inline"
            body["payload"] = packed
        reply = self.core._run(
            self.core.clients.get(tuple(spec.owner)).call("stream_item", body))
        self._stream_consumed[spec.task_id] = reply.get("consumed", 0)
        if reply.get("stop"):
            self._cancelled.add(spec.task_id)  # consumer released the stream
        return shared

    def _stream_backpressure(self, spec: TaskSpec, produced: int) -> None:
        """Pause when the owner's consumer lags more than the configured
        window (spec.backpressure, 0 = unbounded) — ≈ the reference's
        _generator_backpressure_num_objects."""
        if spec.backpressure <= 0:
            return
        while (produced - self._stream_consumed.get(spec.task_id, 0)
               >= spec.backpressure
               and spec.task_id not in self._cancelled):
            # owner-side long-poll: ONE rpc blocks until the consumer
            # reaches the watermark (or 5s passes) instead of hammering
            # the owner's IO loop with 20ms polls
            wait_for = produced - spec.backpressure + 1
            try:
                reply = self.core._run(
                    self.core.clients.get(tuple(spec.owner)).call(
                        "stream_state",
                        {"task_id": spec.task_id.binary(),
                         "wait_for": wait_for, "timeout": 5.0},
                        timeout=30.0))
            except Exception:
                return  # owner gone: stop pausing, let the report fail
            self._stream_consumed[spec.task_id] = reply.get("consumed", 0)
            if reply.get("stop"):
                self._cancelled.add(spec.task_id)
                return

    # -- result reporting (owner is the submitter) --

    def _report_results(self, spec: TaskSpec, values: list) -> None:
        from ray_tpu._private import device_objects

        results = []
        for oid, value in zip(spec.return_ids(), values):
            if (device_objects.is_device_array(value)
                    and value.nbytes >
                    self.core.config.max_direct_call_object_size):
                # Large jax.Array return: keep the HBM here (this worker
                # is the holder), report layout metadata only — no host
                # pickle. The owner frees it via device_free at zero
                # refs; if this worker dies first, lineage re-executes
                # the task. Small arrays stay on the inline path: the
                # host copy is negligible and the value can never be
                # lost with the worker.
                meta = self.core.device_objects.put(oid, value)
                results.append((oid.binary(), "device", {
                    "size": meta.nbytes,
                    "worker_addr": self.core.address,
                    "meta": serialization.dumps(meta)}))
                continue
            smeta, buffers, total = serialization.packed_size(value)
            if total <= self.core.config.max_direct_call_object_size:
                results.append((oid.binary(), "inline",
                                serialization.pack_parts(smeta, buffers)))
            else:
                # piecewise into the arena (no join copy — same path as
                # owner-side put; matters for GiB numpy returns)
                self.core._run(
                    self._store_shared_parts(oid, smeta, buffers, total))
                results.append(
                    (
                        oid.binary(),
                        "shared",
                        {"size": total,
                         "node_addr": self.core.supervisor_addr},
                    )
                )
        self._send_done(spec, {"task_id": spec.task_id.binary(), "results": results})

    async def _store_shared(self, oid: ObjectID, packed: bytes) -> None:
        sup = self.core.clients.get(self.core.supervisor_addr)
        # 600s: a GiB-class create can queue behind another object's
        # spill on the supervisor's store thread
        r = await sup.call("store_create", {"object_id": oid.binary(),
                                            "size": len(packed)},
                           timeout=600)
        self.core.arena.write(r["offset"], packed)
        await sup.call("store_seal", {"object_id": oid.binary()},
                       timeout=600)

    async def _store_shared_parts(self, oid: ObjectID, meta: bytes,
                                  buffers, total: int) -> None:
        """Piecewise arena write of a serialized return — the shared
        create->write->seal helper (no owner bookkeeping: the SUBMITTER
        owns returns; this process only lands the bytes)."""
        await self.core.arena_write_parts(oid, meta, buffers, total)

    def _report_error(self, spec: TaskSpec, err: Exception, retryable: bool) -> None:
        self._send_done(
            spec,
            {
                "task_id": spec.task_id.binary(),
                "error": serialization.dumps(err),
                "retryable": retryable,
            },
        )

    def _send_done(self, spec: TaskSpec, body: dict) -> None:
        """Queue the completion report and return immediately.

        Replies are coalesced: the executor thread never blocks on the
        report roundtrip (it picks up the next task right away), and the
        flusher on the IO loop drains whatever accumulated while the
        previous frame was in flight into ONE `task_done_batch` RPC per
        owner — the reply-side twin of the owner's push_task_batch
        (`ray microbenchmark`'s actor-call envelope needs both sides
        batched; reference: the reply batching inside the C++ direct
        actor transport, `direct_task_transport`)."""
        # report_id makes redelivery safe: a retried report whose first
        # delivery actually landed (reply lost to a transport blip) must
        # not be processed twice — a duplicated retryable-error body would
        # double-requeue the task at the owner
        body["report_id"] = os.urandom(8)
        self._done_outbox.append((tuple(spec.owner), body, 0))
        self.core._run_nowait(self._flush_done())

    async def _flush_done(self) -> None:
        if self._done_flushing:
            return  # one flusher; it will drain what we just queued
        self._done_flushing = True
        try:
            while self._done_outbox:
                by_owner: Dict[tuple, list] = {}
                count = 0
                while self._done_outbox and count < 256:
                    addr, body, attempts = self._done_outbox.popleft()
                    by_owner.setdefault(addr, []).append((body, attempts))
                    count += 1
                # per-owner sends run CONCURRENTLY: one dead owner's RPC
                # timeout must not head-of-line block reports to healthy
                # owners sitting behind it in the outbox
                await asyncio.gather(
                    *(self._send_done_batch(addr, entries)
                      for addr, entries in by_owner.items()))
        finally:
            self._done_flushing = False

    async def _send_done_batch(self, addr: tuple, entries: list) -> None:
        bodies = [b for b, _ in entries]
        try:
            if len(bodies) == 1:
                await self.core.clients.get(addr).call(
                    "task_done", bodies[0])
            else:
                await self.core.clients.get(addr).call(
                    "task_done_batch", {"dones": bodies})
        except Exception:
            # a transient blip must not strand N callers in get():
            # requeue with bounded retries (a dead owner gives up after
            # 3 — its worker-failed handling covers the rest). Backoff
            # rides call_later so the drain loop never sleeps inline.
            retry = [(addr, b, a + 1) for b, a in entries if a + 1 < 3]
            dropped = len(entries) - len(retry)
            if dropped:
                logger.warning(
                    "dropping %d task_done report(s) to %s after 3 "
                    "attempts", dropped, addr)
            if retry:
                def requeue():
                    self._done_outbox.extend(retry)
                    self.core._run_nowait(self._flush_done())

                asyncio.get_running_loop().call_later(0.1, requeue)

    async def _notify_actor_ready(self, spec: TaskSpec) -> None:
        # reconnect-budgeted: the actor CONSTRUCTED — a controller kill +
        # restart window must not fail the creation over the lost ALIVE
        # report. _controller_call shares one (client_id, msg_id) across
        # attempts, and the handler's WAL frame carries that replay key,
        # so a resend that straddles the restart can never
        # double-increment the incarnation (handle seqno reset semantics
        # ride it).
        await self.core._controller_call(
            "actor_ready",
            {
                "actor_id_hex": spec.actor_id.hex(),
                "address": self.core.address,
                "worker_id_hex": self.core.worker_id.hex(),
                "node_id_hex": self.core.node_id_hex,
            },
        )

    async def _notify_creation_failed(self, spec: TaskSpec, err) -> None:
        try:
            await self.core.clients.get(self.core.controller_addr).call(
                "actor_creation_failed",
                {"actor_id_hex": spec.actor_id.hex(), "reason": str(err)[:500]},
            )
        except Exception:
            pass


def _watch_supervisor_liveness(supervisor_pid: int) -> None:
    """Die with the supervisor (≈ raylet-disconnect suicide,
    node_manager.cc:1432 / core_worker exiting on raylet socket close).

    The supervisor is our direct parent; when it dies we are reparented
    (PPID changes). An orphaned worker must not keep serving tasks — the
    cluster has already declared this node dead, and answering actor calls
    from beyond the grave breaks node-death semantics.
    """
    import time as _time

    while True:
        if os.getppid() != supervisor_pid:
            logger.warning("supervisor %d is gone; exiting", supervisor_pid)
            os._exit(1)
        _time.sleep(0.25)


async def _liveness_bond(supervisor_addr) -> None:
    """Hold an open socket to the supervisor; exit the moment it closes.

    The PPID watch above is the backstop, but polling loses the race
    against an in-flight task push — the reference's bond is a *socket*
    (raylet <-> worker), where the kernel delivers EOF the instant the
    raylet dies. Same here: a dedicated idle connection to the
    supervisor's RPC server; EOF or error means the supervisor is gone.
    """
    import asyncio as _asyncio

    # Transient connect errors (accept pressure during a worker burst) must
    # not kill a healthy worker — retry the initial connect; only a
    # post-connect EOF, or persistent refusal, means the supervisor is gone.
    for _ in range(40):
        try:
            reader, _writer = await _asyncio.open_connection(
                supervisor_addr[0], supervisor_addr[1]
            )
            break
        except Exception:
            await _asyncio.sleep(0.25)
    else:
        logger.warning("cannot reach supervisor; exiting")
        os._exit(1)
    try:
        await reader.read()  # returns only at EOF
    except Exception:
        pass
    logger.warning("supervisor connection closed; exiting")
    os._exit(1)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--supervisor", required=True)
    parser.add_argument("--controller", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--arena-path", required=True)
    parser.add_argument("--arena-size", type=int, required=True)
    parser.add_argument("--session-dir", default="")
    args = parser.parse_args()
    if args.session_dir:
        # span files, debug dumps etc. land next to the session's logs.
        # The CLI arg is authoritative: a stale env inherited from an
        # earlier session in the same shell must not win.
        os.environ["RAY_TPU_SESSION_DIR"] = args.session_dir

    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="[worker %(process)d] %(asctime)s %(levelname)s %(message)s",
    )

    def parse_addr(s):
        host, port = s.rsplit(":", 1)
        return (host, int(port))

    threading.Thread(
        target=_watch_supervisor_liveness,
        args=(os.getppid(),),
        name="supervisor-liveness",
        daemon=True,
    ).start()
    # belt over the ppid watch: the supervisor stamps RAY_TPU_OWNER_PID
    # into our env (supervisor.py _spawn_worker); the env watchdog adds
    # the pid-reuse start-time guard the ppid check lacks
    from ray_tpu._private.watchdog import start_owner_watchdog_from_env

    start_owner_watchdog_from_env("worker")

    config = Config.from_env()
    core = CoreWorker(
        config,
        parse_addr(args.controller),
        parse_addr(args.supervisor),
        JobID.from_int(0),
        role="worker",
    )
    core.start()

    executor = Executor(core)
    # replay-cached at the RPC layer (retried delivery replays the ack) on
    # top of the executor's own _seen_pushes task-id dedupe, which covers
    # re-pushes that arrive as NEW requests (owner-level retry paths)
    core.server.register("push_task", executor.push_task, replay_cached=True)
    core.server.register("push_task_batch", executor.push_task_batch,
                         replay_cached=True)
    core.server.register("cancel", executor.cancel)

    async def profile(body):
        """Live in-process profiling (stacks / memory / device HBM);
        ref dashboard reporter_agent.py:391 py-spy attach."""
        from ray_tpu._private import profiling

        return profiling.collect(body.get("kind", "stack"),
                                 body.get("limit", 20))

    core.server.register("profile", profile)

    # p2p collective transport (util/collective/ring.py): register the
    # chunked-frame handler before this worker's address is published
    # anywhere, so no ring segment can ever arrive unroutable
    from ray_tpu.util.collective import ring as _collective_ring

    _collective_ring.ensure_registered(core)

    # make the worker-side public API work inside tasks
    from ray_tpu._private import api

    api._connect_existing(core)

    ok = core._run(
        core.clients.get(parse_addr(args.supervisor)).call(
            "worker_register",
            {
                "worker_id_hex": core.worker_id.hex(),
                "address": core.address,
                "pid": os.getpid(),
                "env_key": os.environ.get("RAY_TPU_WORKER_ENV_KEY", ""),
            },
        )
    )
    asyncio.run_coroutine_threadsafe(
        _liveness_bond(parse_addr(args.supervisor)), core.loop
    )
    # SIGTERM (supervisor shutdown/kill): drain the IO loop before dying
    # so asyncio never reports destroyed-pending tasks into the log tail
    # the driver is still reading
    import signal as _signal

    def _graceful_exit(_sig, _frm):
        try:
            core.shutdown()
        except Exception:
            pass
        # 143 = SIGTERM convention: the supervisor's exit handling and
        # the WORKER_EXITED event must still see a signal-terminated
        # worker, not a clean exit
        os._exit(143)

    _signal.signal(_signal.SIGTERM, _graceful_exit)
    logger.info("worker %s registered, serving", core.worker_id.hex()[:8])
    threading.Event().wait()  # serve forever; supervisor kills us


if __name__ == "__main__":
    main()
