"""Runtime environments: per-task/actor working_dir, py_modules, pip.

Analog of `python/ray/_private/runtime_env/{working_dir,py_modules,pip}.py`:
the driver packages local directories into content-addressed zips uploaded
to the controller KV (≈ the GCS package store,
`runtime_env/packaging.py`); each supervisor materializes them once per
URI under the session dir and spawns workers with

  * cwd = the staged working_dir,
  * PYTHONPATH prepended with working_dir + each py_module parent,
  * for `pip`: a per-requirements-hash venv (--system-site-packages so
    jax & co. resolve from the base image) whose interpreter runs the
    worker (`runtime_env/pip.py` analog; installs run with --no-index
    unless the env sets RAY_TPU_PIP_INDEX — this image has no egress).

`env_vars` stays supported as before.
"""

from __future__ import annotations

import asyncio
import hashlib
import io
import logging
import os
import subprocess
import sys
import zipfile
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

_EXCLUDE_DIRS = {".git", "__pycache__", ".venv", "node_modules"}
MAX_PACKAGE_BYTES = 256 * 1024 * 1024


# ------------------------------------------------------------------ driver


def package_local_path(path: str) -> Tuple[str, bytes]:
    """Zip a local file/dir into a deterministic, content-addressed blob.
    Returns (uri, zip_bytes); uri is 'pkg_<sha256[:32]>'."""
    path = os.path.abspath(path)
    if not os.path.exists(path):
        raise FileNotFoundError(f"runtime_env path does not exist: {path}")
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        if os.path.isfile(path):
            zf.write(path, os.path.basename(path))
        else:
            base = os.path.basename(path.rstrip("/")) or "pkg"
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
                for f in sorted(files):
                    full = os.path.join(root, f)
                    rel = os.path.join(base, os.path.relpath(full, path))
                    zf.write(full, rel)
    blob = buf.getvalue()
    if len(blob) > MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package {path} is {len(blob)} bytes "
            f"(limit {MAX_PACKAGE_BYTES}); exclude large data")
    uri = "pkg_" + hashlib.sha256(blob).hexdigest()[:32]
    return uri, blob


def resolve_runtime_env(env: Optional[Dict[str, Any]], core) -> Optional[Dict[str, Any]]:
    """Driver-side normalization: local paths become uploaded KV URIs so
    the spec shipped in every TaskSpec is small and location-independent.
    Idempotent for already-resolved specs."""
    if not env:
        return env
    out = dict(env)
    uploads: List[Tuple[str, bytes]] = []

    def upload_path(p: str) -> str:
        uri, blob = package_local_path(p)
        uploads.append((uri, blob))
        return uri

    wd = out.get("working_dir")
    if wd and not str(wd).startswith("pkg_"):
        out["working_dir"] = upload_path(wd)
    mods = out.get("py_modules")
    if mods:
        out["py_modules"] = [
            m if str(m).startswith("pkg_") else upload_path(m) for m in mods
        ]
    pip = out.get("pip")
    if pip is not None:
        if isinstance(pip, str):
            pip = [line.strip() for line in open(pip) if line.strip()]
        out["pip"] = list(pip)

    async def put_all():
        ctrl = core.clients.get(core.controller_addr)
        for uri, blob in uploads:
            exists = await ctrl.call("kv_exists", {"ns": "pkg", "key": uri})
            if not exists:
                await ctrl.call(
                    "kv_put",
                    {"ns": "pkg", "key": uri, "value": blob,
                     "overwrite": False})

    if uploads:
        core._run(put_all())
    return out


# -------------------------------------------------------------- supervisor


class WorkerEnvSpec:
    """What _spawn_worker needs: interpreter, cwd, extra env, and (for
    container envs) how to wrap the worker command in an engine run."""

    def __init__(self, python: str = sys.executable,
                 cwd: Optional[str] = None,
                 env_vars: Optional[Dict[str, str]] = None):
        self.python = python
        self.cwd = cwd
        self.env_vars = env_vars or {}
        # set for container runtime envs: {"engine","image","run_options"}
        self.container: Optional[Dict[str, Any]] = None
        # env-files minted by wrap_command, pending deletion by the
        # spawner once the engine has consumed them (they hold secrets)
        self.env_files: List[str] = []

    def wrap_command(self, cmd: List[str], env: Dict[str, str],
                     mounts: List[str],
                     env_file_dir: Optional[str] = None) -> List[str]:
        """Wrap the worker argv in an engine invocation (ref
        `python/ray/_private/runtime_env/container.py` worker-command
        injection). Host networking + IPC so the worker reaches the
        supervisor/controller sockets and maps the /dev/shm arena.

        Env is forwarded through a 0600 ``--env-file``, NOT ``--env k=v``
        argv: the worker env carries secrets (user env_vars, cloud
        credentials inherited from the driver), and argv is world-readable
        through ``ps``/``/proc/<pid>/cmdline`` for the lifetime of the
        engine client process."""
        if not self.container:
            return cmd
        argv = [self.container["engine"], "run", "--rm",
                "--network=host", "--ipc=host"]
        for m in mounts:
            argv += ["-v", f"{m}:{m}"]
        if self.cwd:
            argv += ["--workdir", self.cwd]
        import tempfile

        fd, env_path = tempfile.mkstemp(  # mkstemp => mode 0600
            prefix="rtpu_env_", suffix=".env", dir=env_file_dir)
        with os.fdopen(fd, "w") as f:
            for k, v in env.items():
                if "\n" in k or "\n" in str(v):
                    # the env-file format is line-based; a newline value
                    # cannot be represented — drop it rather than corrupt
                    # the vars after it
                    logger.warning(
                        "container env var %s dropped (embedded newline)",
                        k)
                    continue
                f.write(f"{k}={v}\n")
        self.env_files.append(env_path)
        argv += ["--env-file", env_path]
        argv += list(self.container.get("run_options") or [])
        argv.append(self.container["image"])
        return argv + cmd


class RuntimeEnvManager:
    """Materializes runtime env resources once per URI/hash on one node
    (≈ the per-node runtime env agent, `runtime_env/agent/`)."""

    def __init__(self, session_dir: str, node_tag: str, kv_get):
        """kv_get: async (ns, key) -> bytes | None (controller KV)."""
        self._root = os.path.join(session_dir, "runtime_envs", node_tag)
        os.makedirs(self._root, exist_ok=True)
        self._kv_get = kv_get
        self._locks: Dict[str, asyncio.Lock] = {}
        self._ready: Dict[str, str] = {}  # uri/hash -> staged path

    def _lock(self, key: str) -> asyncio.Lock:
        if key not in self._locks:
            self._locks[key] = asyncio.Lock()
        return self._locks[key]

    async def setup(self, runtime_env: Optional[Dict[str, Any]]) -> WorkerEnvSpec:
        spec = WorkerEnvSpec()
        if not runtime_env:
            return spec
        paths: List[str] = []
        wd = runtime_env.get("working_dir")
        if wd:
            staged = await self._ensure_package(wd)
            # the zip wraps a single top-level dir (or file) — the
            # working dir is that entry
            entries = os.listdir(staged)
            spec.cwd = (os.path.join(staged, entries[0])
                        if len(entries) == 1 else staged)
            paths.append(spec.cwd)
        for uri in runtime_env.get("py_modules") or []:
            staged = await self._ensure_package(uri)
            paths.append(staged)
        pip = runtime_env.get("pip")
        conda = runtime_env.get("conda")
        if pip and conda:
            raise ValueError(
                "runtime_env: 'pip' and 'conda' are mutually exclusive "
                "(install pip packages inside the conda spec)")
        if pip:
            spec.python = await self._ensure_venv(pip)
        if conda:
            spec.python = await self._ensure_conda(conda)
        container = runtime_env.get("container")
        if container:
            spec.container = self._container_spec(container)
        if paths:
            spec.env_vars["RAY_TPU_RUNTIME_ENV_PYTHONPATH"] = os.pathsep.join(
                paths)
        return spec

    async def _ensure_conda(self, conda) -> str:
        """Conda env interpreter (ref
        `python/ray/_private/runtime_env/conda.py`): a string names an
        EXISTING env (or prefix path); a dict is an environment spec
        created once per content hash under the session dir. Gated on a
        conda binary (RAY_TPU_CONDA_EXE overrides discovery)."""
        import shutil

        conda_exe = os.environ.get("RAY_TPU_CONDA_EXE") or \
            shutil.which("conda") or shutil.which("mamba")
        if not conda_exe:
            raise RuntimeError(
                "runtime_env 'conda' requires a conda/mamba binary on "
                "PATH (or RAY_TPU_CONDA_EXE); none found on this node")
        if isinstance(conda, str):
            # named env or explicit prefix path
            if os.sep in conda:
                prefix = conda
            else:
                base = (await self._run_out(
                    [conda_exe, "info", "--base"])).strip()
                prefix = os.path.join(base, "envs", conda)
            python = os.path.join(prefix, "bin", "python")
            if not os.path.exists(python):
                raise RuntimeError(
                    f"conda env {conda!r} has no interpreter at {python}")
            return python
        # dict spec -> content-addressed created env
        import json

        key = "conda_" + hashlib.sha256(
            json.dumps(conda, sort_keys=True).encode()).hexdigest()[:16]
        async with self._lock(key):
            ready = self._ready.get(key)
            if ready:
                return ready
            prefix = os.path.join(self._root, key)
            python = os.path.join(prefix, "bin", "python")
            if not os.path.exists(python):
                spec_path = os.path.join(self._root, key + ".yml")
                with open(spec_path, "w") as f:
                    f.write(_conda_spec_yaml(conda))
                await self._run_cmd([conda_exe, "env", "create", "-y",
                                     "-p", prefix, "-f", spec_path])
                if not os.path.exists(python):
                    raise RuntimeError(
                        f"conda env create produced no interpreter "
                        f"at {python}")
            self._ready[key] = python
            return python

    @staticmethod
    def _container_spec(container) -> Dict[str, Any]:
        """Validate + resolve the container engine (ref
        `python/ray/_private/runtime_env/container.py`). Gated on a
        podman/docker binary (RAY_TPU_CONTAINER_RUNTIME overrides)."""
        import shutil

        if isinstance(container, str):
            container = {"image": container}
        image = container.get("image")
        if not image:
            raise ValueError("runtime_env 'container' needs an 'image'")
        engine = os.environ.get("RAY_TPU_CONTAINER_RUNTIME") or \
            shutil.which("podman") or shutil.which("docker")
        if not engine:
            raise RuntimeError(
                "runtime_env 'container' requires podman or docker on "
                "PATH (or RAY_TPU_CONTAINER_RUNTIME); none found")
        return {"engine": engine, "image": image,
                "run_options": list(container.get("run_options") or [])}

    @staticmethod
    async def _run_out(cmd: List[str]) -> str:
        proc = await asyncio.create_subprocess_exec(
            *cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        out, _ = await proc.communicate()
        if proc.returncode != 0:
            raise RuntimeError(
                f"runtime_env command failed ({' '.join(cmd[:4])}): "
                f"{out.decode(errors='replace')[-2000:]}")
        return out.decode(errors="replace")

    async def _ensure_package(self, uri: str) -> str:
        async with self._lock(uri):
            staged = self._ready.get(uri)
            if staged:
                return staged
            dest = os.path.join(self._root, uri)
            if not os.path.isdir(dest):
                blob = await self._kv_get("pkg", uri)
                if blob is None:
                    raise RuntimeError(
                        f"runtime_env package {uri} not in cluster KV")
                tmp = dest + ".tmp"
                os.makedirs(tmp, exist_ok=True)
                with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                    zf.extractall(tmp)
                os.replace(tmp, dest)
            self._ready[uri] = dest
            return dest

    async def _ensure_venv(self, requirements: List[str]) -> str:
        key = "venv_" + hashlib.sha256(
            "\n".join(sorted(requirements)).encode()).hexdigest()[:16]
        async with self._lock(key):
            ready = self._ready.get(key)
            if ready:
                return ready
            venv_dir = os.path.join(self._root, key)
            python = os.path.join(venv_dir, "bin", "python")
            if not os.path.exists(python):
                await self._run_cmd(
                    [sys.executable, "-m", "venv",
                     "--system-site-packages", venv_dir])
                # --system-site-packages chains to the BASE interpreter; if
                # we ourselves run in a venv (/opt/venv with jax etc.), its
                # site dirs are lost. Inherit the parent's import paths via
                # a .pth so the env venv sees everything this process does.
                sp = os.path.join(
                    venv_dir, "lib",
                    f"python{sys.version_info.major}.{sys.version_info.minor}",
                    "site-packages")
                parent_paths = [
                    p for p in sys.path
                    if p and os.path.isdir(p) and "zip" not in p
                ]
                with open(os.path.join(sp, "_rtpu_inherit.pth"), "w") as f:
                    f.write("\n".join(parent_paths) + "\n")
                pip_cmd = [python, "-m", "pip", "install",
                           "--no-warn-script-location"]
                index = os.environ.get("RAY_TPU_PIP_INDEX", "")
                if index:
                    pip_cmd += ["--index-url", index]
                else:
                    # no egress in this image: local paths/wheels only, and
                    # build isolation would try to fetch setuptools
                    pip_cmd += ["--no-index", "--no-build-isolation"]
                pip_cmd += list(requirements)
                await self._run_cmd(pip_cmd)
            self._ready[key] = python
            return python

    @staticmethod
    async def _run_cmd(cmd: List[str]) -> None:
        proc = await asyncio.create_subprocess_exec(
            *cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        out, _ = await proc.communicate()
        if proc.returncode != 0:
            raise RuntimeError(
                f"runtime_env command failed ({' '.join(cmd[:4])}...): "
                f"{out.decode(errors='replace')[-2000:]}")


def _conda_spec_yaml(spec: Dict[str, Any]) -> str:
    """Minimal YAML emitter for conda environment specs (name,
    channels, dependencies incl. nested pip lists) — avoids a yaml
    dependency for the one shape `conda env create -f` accepts."""
    lines = []
    if spec.get("name"):
        lines.append(f"name: {spec['name']}")
    for key in ("channels", "dependencies"):
        vals = spec.get(key)
        if not vals:
            continue
        lines.append(f"{key}:")
        for v in vals:
            if isinstance(v, dict):  # {"pip": [...]}
                for k2, sub in v.items():
                    lines.append(f"  - {k2}:")
                    for s in sub:
                        lines.append(f"      - {s}")
            else:
                lines.append(f"  - {v}")
    return "\n".join(lines) + "\n"


def runtime_env_cache_key(runtime_env: Optional[Dict[str, Any]]) -> tuple:
    """The parts of a runtime env that make worker processes
    non-interchangeable (used in the supervisor's worker-pool env key)."""
    if not runtime_env:
        return ()
    conda = runtime_env.get("conda")
    container = runtime_env.get("container")
    if isinstance(container, str):
        container = {"image": container}
    return (
        runtime_env.get("working_dir") or "",
        tuple(runtime_env.get("py_modules") or ()),
        tuple(sorted(runtime_env.get("pip") or ())),
        tuple(sorted((runtime_env.get("env_vars") or {}).items())),
        repr(conda) if conda else "",
        (container.get("image"),
         tuple(container.get("run_options") or ())) if container else (),
    )
