"""Minimal asyncio HTTP server for daemon endpoints (/metrics, /healthz).

The daemons' RPC substrate is a binary protocol (rpc.py); Prometheus and
humans speak HTTP. This is a deliberately tiny HTTP/1.0 responder — one
request per connection, GET only — sufficient for scrape endpoints
(≈ the reference's metrics agent exposing the Prometheus port).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, Dict, Optional, Tuple

logger = logging.getLogger(__name__)


class HttpNotFound(Exception):
    """Raise from a handler to produce a 404 instead of a 500."""


class MetricsHttpServer:
    """Tiny route table over HTTP/1.0.

    Handlers are registered per (method, path); a path ending in '/*'
    matches any suffix (passed as `tail`). A handler may be sync or
    async and returns (content_type, text); JSON handlers can return a
    plain dict/list which is serialized for them. POST handlers receive
    (body_bytes, tail); GET handlers receive (tail) when their route is
    a prefix route, else no args — introspected by arity.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._routes: Dict[Tuple[str, str], Callable] = {}
        self._server: Optional[asyncio.AbstractServer] = None

    def route(self, path: str, handler: Callable, method: str = "GET"):
        self._routes[(method.upper(), path)] = handler

    @property
    def port(self) -> int:
        return self._port

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._handle, self._host, self._port)
        self._port = self._server.sockets[0].getsockname()[1]
        return self._port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=2)
            except Exception:
                pass

    def _match(self, method: str, path: str):
        exact = self._routes.get((method, path))
        if exact is not None:
            return exact, None
        for (m, pat), handler in self._routes.items():
            if m == method and pat.endswith("/*") and \
                    path.startswith(pat[:-1]):
                return handler, path[len(pat) - 1:]
        return None, None

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            line = await asyncio.wait_for(reader.readline(), timeout=10)
            parts = line.decode("latin1").split()
            method = parts[0].upper() if parts else "GET"
            path = parts[1].split("?")[0] if len(parts) >= 2 else "/"
            clen = 0
            while True:
                h = await asyncio.wait_for(reader.readline(), timeout=10)
                if h in (b"\r\n", b"\n", b""):
                    break
                if h.lower().startswith(b"content-length:"):
                    clen = int(h.split(b":", 1)[1].strip())
            req_body = (await asyncio.wait_for(
                reader.readexactly(clen), timeout=30)) if clen else b""

            handler, tail = self._match(method, path)
            if handler is None:
                self._write(writer, 404, "text/plain", "not found")
                return
            args = []
            if method in ("POST", "PUT", "DELETE"):
                args.append(req_body)
            if tail is not None:
                args.append(tail)
            try:
                result = handler(*args)
                if asyncio.iscoroutine(result):
                    result = await result
                if isinstance(result, tuple):
                    ctype, text = result
                else:  # plain JSON-able value
                    import json as _json

                    ctype, text = "application/json", _json.dumps(result)
                self._write(writer, 200, ctype, text)
            except HttpNotFound as e:
                self._write(writer, 404, "text/plain", str(e))
            except Exception as e:  # noqa: BLE001 — surface as 500
                logger.debug("http handler failed", exc_info=True)
                self._write(writer, 500, "text/plain",
                            f"{type(e).__name__}: {e}")
            await writer.drain()
        except Exception:
            logger.debug("http request failed", exc_info=True)
        finally:
            try:
                writer.close()
            except Exception:
                pass

    @staticmethod
    def _write(writer, status: int, ctype: str, text: str) -> None:
        body = text.encode()
        reason = {200: "OK", 404: "Not Found", 500: "Error"}.get(
            status, "OK")
        head = (f"HTTP/1.0 {status} {reason}\r\nContent-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n\r\n")
        writer.write(head.encode("latin1") + body)
