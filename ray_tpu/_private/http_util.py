"""Minimal asyncio HTTP server for daemon endpoints (/metrics, /healthz).

The daemons' RPC substrate is a binary protocol (rpc.py); Prometheus and
humans speak HTTP. This is a deliberately tiny HTTP/1.0 responder — one
request per connection, GET only — sufficient for scrape endpoints
(≈ the reference's metrics agent exposing the Prometheus port).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, Dict, Optional, Tuple

logger = logging.getLogger(__name__)


class MetricsHttpServer:
    """Routes GET paths to handlers returning (content_type, body)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._routes: Dict[str, Callable[[], Tuple[str, str]]] = {}
        self._server: Optional[asyncio.AbstractServer] = None

    def route(self, path: str, handler: Callable[[], Tuple[str, str]]):
        self._routes[path] = handler

    @property
    def port(self) -> int:
        return self._port

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._handle, self._host, self._port)
        self._port = self._server.sockets[0].getsockname()[1]
        return self._port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=2)
            except Exception:
                pass

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            line = await asyncio.wait_for(reader.readline(), timeout=10)
            parts = line.decode("latin1").split()
            path = parts[1].split("?")[0] if len(parts) >= 2 else "/"
            # drain headers
            while True:
                h = await asyncio.wait_for(reader.readline(), timeout=10)
                if h in (b"\r\n", b"\n", b""):
                    break
            handler = self._routes.get(path)
            if handler is None:
                body = b"not found"
                head = (f"HTTP/1.0 404 Not Found\r\nContent-Length: "
                        f"{len(body)}\r\n\r\n")
            else:
                ctype, text = handler()
                body = text.encode()
                head = (f"HTTP/1.0 200 OK\r\nContent-Type: {ctype}\r\n"
                        f"Content-Length: {len(body)}\r\n\r\n")
            writer.write(head.encode("latin1") + body)
            await writer.drain()
        except Exception:
            logger.debug("metrics http request failed", exc_info=True)
        finally:
            try:
                writer.close()
            except Exception:
                pass
