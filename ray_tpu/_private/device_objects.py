"""Device-array objects: jax.Arrays through the object layer without a
host round-trip at put time.

TPU-first answer to the reference's compiled-DAG mutable plasma channels
(`python/ray/experimental/channel.py:76`,
`src/ray/core_worker/experimental_mutable_object_manager.h:36`) and to
SURVEY §7 hard part 2. The reference moves tensors between processes by
copying them into mutable shared-memory buffers; on TPU the data already
lives in HBM with a sharding layout, so the object layer should *keep*
it there:

- ``put()`` of a jax.Array records only metadata (global shape/dtype +
  mesh axes + partition spec) and parks the array in the owner's
  process-local registry — HBM ownership stays with the worker, nothing
  is serialized.
- ``get()`` by the owner is a registry lookup: zero-copy, zero host
  traffic.
- ``get()`` by another process streams each addressable shard's host
  staging buffer in bounded chunks and re-materializes on the reader's
  devices with the *same logical sharding* (equivalent local mesh built
  from the recorded axes). ``jax.device_put`` dispatches asynchronously,
  so shard k uploads while shard k+1's bytes are still arriving — the
  double-buffered pinned-host pattern.
- Owner-based GC: when the ref count hits zero the registry entry drops
  and XLA frees the HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

# index key: ((start, stop) per dim) — the normalized form of a shard's
# global-slice index, stable across sender and receiver
IndexKey = Tuple[Tuple[int, int], ...]


def is_device_array(value: Any) -> bool:
    """True for a jax.Array (any sharding), without importing jax for
    non-array values (the object layer must stay importable — and fast —
    in processes that never touch a device)."""
    mod = type(value).__module__ or ""
    if not (mod.startswith("jax") or mod.startswith("jaxlib")):
        return False
    try:
        import jax

        # tracers subclass jax.Array but have no committed buffers
        return (isinstance(value, jax.Array)
                and not isinstance(value, jax.core.Tracer))
    except Exception:
        return False


@dataclasses.dataclass
class DeviceArrayMeta:
    """Wire-serializable description of a device array's layout."""

    shape: Tuple[int, ...]
    dtype: str
    nbytes: int
    # mesh axes as ((name, size), ...) — None for single-device arrays
    mesh_axes: Optional[Tuple[Tuple[str, int], ...]]
    # partition spec entries: None, axis name, or tuple of axis names
    pspec: Optional[Tuple[Any, ...]]
    # per-shard global-slice indices + byte sizes, one per distinct shard
    shards: List[Tuple[IndexKey, int]] = dataclasses.field(
        default_factory=list)


def _norm_index(index, shape) -> IndexKey:
    """Normalize a shard's tuple-of-slices index to ((start, stop), ...)."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    # scalar/0-d arrays have empty indices
    return tuple(out)


def extract_meta(arr) -> DeviceArrayMeta:
    from jax.sharding import NamedSharding

    mesh_axes = None
    pspec = None
    sharding = arr.sharding
    if isinstance(sharding, NamedSharding):
        mesh = sharding.mesh
        mesh_axes = tuple((str(n), int(s))
                          for n, s in zip(mesh.axis_names, mesh.devices.shape))
        pspec = tuple(
            tuple(p) if isinstance(p, (tuple, list)) else p
            for p in sharding.spec)
    seen: Dict[IndexKey, int] = {}
    for sh in arr.addressable_shards:
        key = _norm_index(sh.index, arr.shape)
        if key not in seen:
            seen[key] = int(sh.data.nbytes)
    return DeviceArrayMeta(
        shape=tuple(int(d) for d in arr.shape),
        dtype=str(arr.dtype),
        nbytes=int(arr.nbytes) if arr.size else 0,
        mesh_axes=mesh_axes,
        pspec=pspec,
        shards=list(seen.items()),
    )


def shard_host_bytes(arr, index_key: IndexKey) -> bytes:
    """Host staging buffer for the shard at *index_key* (first match —
    replicated shards are bit-identical)."""
    import numpy as np

    for sh in arr.addressable_shards:
        if _norm_index(sh.index, arr.shape) == index_key:
            return np.ascontiguousarray(np.asarray(sh.data)).tobytes()
    raise KeyError(f"no addressable shard at {index_key}")


def _equivalent_local_mesh(mesh_axes):
    """Build a local mesh with the recorded axis names/sizes from this
    process's devices; None when not enough devices are attached."""
    import math

    import jax
    from jax.sharding import Mesh

    need = math.prod(s for _, s in mesh_axes) if mesh_axes else 1
    devices = jax.devices()
    if len(devices) < need:
        return None
    import numpy as np

    names = tuple(n for n, _ in mesh_axes)
    sizes = tuple(s for _, s in mesh_axes)
    return Mesh(np.array(devices[:need]).reshape(sizes), names)


def assemble(meta: DeviceArrayMeta,
             shard_data: Dict[IndexKey, bytes]):
    """Re-materialize a device array from per-shard host buffers.

    With enough local devices the array comes back with the SAME logical
    sharding (axis names, sizes, partition spec) over this process's
    devices; otherwise it lands on the default device. device_put calls
    dispatch asynchronously, so the per-shard uploads overlap with any
    remaining network reads the caller is still doing.
    """
    import numpy as np

    dtype = np.dtype(meta.dtype)

    def shard_np(key: IndexKey) -> "np.ndarray":
        shape = tuple(stop - start for start, stop in key)
        return np.frombuffer(shard_data[key], dtype=dtype).reshape(shape)

    try:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
    except Exception:  # no jax in this process: plain numpy fallback
        return _assemble_numpy(meta, shard_np)

    if meta.mesh_axes:
        mesh = _equivalent_local_mesh(meta.mesh_axes)
        if mesh is not None:
            spec = PartitionSpec(*(meta.pspec or ()))
            sharding = NamedSharding(mesh, spec)
            index_map = sharding.devices_indices_map(meta.shape)
            bufs = []
            try:
                for dev, index in index_map.items():
                    key = _norm_index(index, meta.shape)
                    bufs.append(jax.device_put(shard_np(key), dev))
                return jax.make_array_from_single_device_arrays(
                    meta.shape, sharding, bufs)
            except KeyError:
                # sender shard layout didn't line up (e.g. partial
                # addressability); fall through to single-device
                pass
    return jax.device_put(_assemble_numpy(meta, shard_np))


def _assemble_numpy(meta: DeviceArrayMeta, shard_np):
    import math

    import numpy as np

    if len(meta.shards) == 1:
        key = meta.shards[0][0]
        full = shard_np(key)
        if tuple(stop - start for start, stop in key) == meta.shape:
            return full
    # the recorded shards must tile the whole global shape (NamedSharding
    # slices partition cleanly, so element counts suffice) — a partial
    # view (e.g. a sender that addressed only part of a multi-host array)
    # must fail loudly, never return np.empty() garbage
    covered = sum(
        math.prod(stop - start for start, stop in key)
        for key, _ in meta.shards)
    total = math.prod(meta.shape)
    if covered != total:
        raise ValueError(
            f"device object shards cover {covered}/{total} elements — "
            "sender did not address the full array")
    out = np.empty(meta.shape, dtype=np.dtype(meta.dtype))
    for key, _ in meta.shards:
        out[tuple(slice(start, stop) for start, stop in key)] = shard_np(key)
    return out


class DeviceObjectRegistry:
    """Holder-side HBM registry: oid -> live jax.Array (+ a tiny host
    staging cache for in-flight remote reads, so a multi-chunk shard
    transfer converts device->host once, not once per chunk).

    ``read`` runs on executor threads while ``put``/``drop`` run on the
    event loop — every mutation holds the lock."""

    _STAGE_CACHE = 2

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._arrays: Dict[Any, Any] = {}
        self._meta: Dict[Any, DeviceArrayMeta] = {}
        self._stage: "Dict[Tuple[Any, IndexKey], bytes]" = {}

    def put(self, oid, arr) -> DeviceArrayMeta:
        meta = extract_meta(arr)
        with self._lock:
            self._arrays[oid] = arr
            self._meta[oid] = meta
        return meta

    def get(self, oid):
        with self._lock:
            return self._arrays.get(oid)

    def meta(self, oid) -> Optional[DeviceArrayMeta]:
        with self._lock:
            return self._meta.get(oid)

    def read(self, oid, index_key: IndexKey, offset: int,
             length: int) -> bytes:
        cache_key = (oid, index_key)
        with self._lock:
            buf = self._stage.get(cache_key)
            arr = self._arrays.get(oid)
        if buf is None:
            if arr is None:
                raise KeyError(f"device object {oid!r} released")
            # device->host staging outside the lock (can be many MB)
            buf = shard_host_bytes(arr, index_key)
            with self._lock:
                while len(self._stage) >= self._STAGE_CACHE:
                    self._stage.pop(next(iter(self._stage)), None)
                self._stage[cache_key] = buf
        chunk = buf[offset:offset + length]
        if offset + length >= len(buf):  # last chunk: staging done
            with self._lock:
                self._stage.pop(cache_key, None)
        return chunk

    def drop(self, oid) -> bool:
        """GC: releasing the registry reference frees the HBM."""
        with self._lock:
            self._meta.pop(oid, None)
            for k in [k for k in self._stage if k[0] == oid]:
                self._stage.pop(k, None)
            return self._arrays.pop(oid, None) is not None

    def __len__(self) -> int:
        with self._lock:
            return len(self._arrays)
