"""External spill storage — pluggable backends behind one URI interface.

Analog of the reference's `python/ray/_private/external_storage.py:496`
(`ExternalStorage` + filesystem/S3 implementations behind
`object_spilling_config`): the node object store spills cold objects
through whichever backend the spill URI selects, so spill capacity can
live on a local disk, a remote object store, or (in tests) a fake remote.

Backends by scheme:
  - ``""`` / ``file://``  — local filesystem directory (default)
  - ``mock://``           — fake remote store for tests: same URI contract
                            as a real remote (opaque returned URIs, no
                            local-path semantics), backed by a directory
                            plus op counters
  - ``s3://``             — S3-class object storage via boto3 when
                            available (gated: this image has no boto3, so
                            constructing it raises with a clear message)
"""

from __future__ import annotations

import os
import uuid
from typing import Dict


class ExternalStorage:
    """One spilled object = one (key, payload) in the backend. `put`
    returns an opaque URI that `get`/`delete` accept — callers must not
    parse it (a remote backend's URIs carry no local meaning). `data` is
    bytes-like (often a memoryview into the arena — backends that need
    real bytes copy themselves)."""

    def put(self, key: str, data) -> str:
        raise NotImplementedError

    def get(self, uri: str) -> bytes:
        raise NotImplementedError

    def list_keys(self, prefix: str):
        """(key, uri) pairs for stored objects whose key starts with
        *prefix* — the discovery primitive control-plane recovery needs
        (every real object store has a list op). Latest write per key
        wins when a backend versions its objects."""
        raise NotImplementedError

    def delete(self, uri: str) -> None:
        raise NotImplementedError


class FileSystemStorage(ExternalStorage):
    """Spill to a local directory (the default backend)."""

    def __init__(self, base_dir: str):
        self._dir = base_dir
        os.makedirs(base_dir, exist_ok=True)

    def put(self, key: str, data: bytes) -> str:
        path = os.path.join(self._dir, key)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # readers never see a half-written spill
        return "file://" + path

    def get(self, uri: str) -> bytes:
        with open(uri[len("file://"):], "rb") as f:
            return f.read()

    def list_keys(self, prefix: str):
        try:
            names = os.listdir(self._dir)
        except OSError:
            return []
        return [(n, "file://" + os.path.join(self._dir, n))
                for n in names
                if n.startswith(prefix) and not n.endswith(".tmp")]

    def delete(self, uri: str) -> None:
        try:
            os.unlink(uri[len("file://"):])
        except OSError:
            pass


class MockRemoteStorage(ExternalStorage):
    """Fake remote object store for tests: honors the exact URI contract
    of a real remote (opaque URIs with a random token, so any caller
    that treats them as paths breaks loudly) and counts operations."""

    def __init__(self, base_dir: str):
        self._dir = base_dir
        os.makedirs(base_dir, exist_ok=True)
        self.puts = 0
        self.gets = 0
        self.deletes = 0

    def _path(self, uri: str) -> str:
        assert uri.startswith("mock://"), uri
        return os.path.join(self._dir, uri[len("mock://"):])

    def put(self, key: str, data: bytes) -> str:
        self.puts += 1
        token = f"{key}-{uuid.uuid4().hex[:8]}"
        with open(os.path.join(self._dir, token), "wb") as f:
            f.write(data)
        uri = "mock://" + token
        # durable key index (a real remote serves list from its own
        # metadata; the fake needs one so a NEW process can discover
        # keys after the writer died — the control-plane recovery path)
        with open(os.path.join(self._dir, "_index"), "a") as f:
            f.write(f"{key}\t{uri}\n")
        return uri

    def get(self, uri: str) -> bytes:
        self.gets += 1
        with open(self._path(uri), "rb") as f:
            return f.read()

    def list_keys(self, prefix: str):
        out = {}
        try:
            with open(os.path.join(self._dir, "_index")) as f:
                for line in f:
                    key, _, uri = line.rstrip("\n").partition("\t")
                    if key.startswith(prefix) and uri:
                        out[key] = uri  # latest write per key wins
        except OSError:
            return []
        # drop entries whose object was deleted
        return [(k, u) for k, u in out.items()
                if os.path.exists(self._path(u))]

    def delete(self, uri: str) -> None:
        self.deletes += 1
        try:
            os.unlink(self._path(uri))
        except OSError:
            pass


class S3Storage(ExternalStorage):
    """S3-class backend (``s3://bucket/prefix``). Requires boto3, which
    this image does not ship — the class exists so a deployment with
    boto3 gets the full path, and everyone else a clear error."""

    def __init__(self, uri: str):
        try:
            import boto3  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "s3:// spill requires boto3, which is not installed; "
                "use a file:// or local-directory spill target") from e
        rest = uri[len("s3://"):]
        self._bucket, _, self._prefix = rest.partition("/")
        import boto3

        self._client = boto3.client("s3")

    def _key(self, uri: str) -> str:
        return uri[len("s3://") + len(self._bucket) + 1:]

    def put(self, key: str, data: bytes) -> str:
        full = (self._prefix + "/" + key).lstrip("/")
        self._client.put_object(Bucket=self._bucket, Key=full, Body=data)
        return f"s3://{self._bucket}/{full}"

    def get(self, uri: str) -> bytes:
        out = self._client.get_object(Bucket=self._bucket,
                                      Key=self._key(uri))
        return out["Body"].read()

    def list_keys(self, prefix: str):
        full = (self._prefix + "/" + prefix).lstrip("/")
        out = []
        token = None
        while True:
            kw = {"Bucket": self._bucket, "Prefix": full}
            if token:
                kw["ContinuationToken"] = token
            resp = self._client.list_objects_v2(**kw)
            for obj in resp.get("Contents", []):
                key = obj["Key"]
                short = key[len(self._prefix) + 1:] if self._prefix else key
                out.append((short, f"s3://{self._bucket}/{key}"))
            if not resp.get("IsTruncated"):
                return out
            token = resp.get("NextContinuationToken")

    def delete(self, uri: str) -> None:
        try:
            self._client.delete_object(Bucket=self._bucket,
                                       Key=self._key(uri))
        except Exception:
            pass


def storage_from_spill_target(target: str, default_dir: str
                              ) -> ExternalStorage:
    """Build the backend for a spill target (config.object_spilling_uri):
    empty -> local default dir; file:///path or /path -> that dir;
    mock://dir -> fake remote; s3://... -> S3."""
    if not target:
        return FileSystemStorage(default_dir)
    if target.startswith("file://"):
        return FileSystemStorage(target[len("file://"):])
    if target.startswith("mock://"):
        return MockRemoteStorage(target[len("mock://"):])
    if target.startswith("s3://"):
        return S3Storage(target)
    if "://" not in target:
        return FileSystemStorage(target)
    raise ValueError(f"unsupported spill target {target!r}")
