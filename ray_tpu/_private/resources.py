"""Resource accounting.

Analog of the reference's scheduling resource model
(`src/ray/raylet/scheduling/cluster_resource_manager`, `NodeResources`):
a node advertises a map of resource name → float capacity; tasks/actors demand
resource maps; placement-group bundles reserve slices and re-expose them under
formatted names.

TPU-first: chips are a first-class resource ("TPU"), and a whole ICI slice is
gang-schedulable via the "TPU-<topology>-head" resource convention the
reference introduced for multi-host TPU pods
(`python/ray/_private/accelerators/tpu.py:44-49`) — a pod-slice job grabs the
head resource on host 0 and per-host "TPU" chips elsewhere.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

EPS = 1e-9

CPU = "CPU"
TPU = "TPU"
MEMORY = "memory"
OBJECT_STORE_MEMORY = "object_store_memory"


class ResourceSet(dict):
    """A {name: amount} map with arithmetic. Amounts are floats >= 0."""

    @classmethod
    def of(cls, mapping: Optional[Dict[str, float]]) -> "ResourceSet":
        rs = cls()
        if mapping:
            for k, v in mapping.items():
                if v < 0:
                    raise ValueError(f"negative resource {k}={v}")
                if v > 0:
                    rs[k] = float(v)
        return rs

    def fits(self, other: "ResourceSet") -> bool:
        """True if self has at least `other` of every resource."""
        return all(self.get(k, 0.0) + EPS >= v for k, v in other.items())

    def subtract(self, other: "ResourceSet") -> None:
        for k, v in other.items():
            cur = self.get(k, 0.0) - v
            if cur < -EPS:
                raise ValueError(f"resource {k} went negative ({cur})")
            if cur <= EPS:
                self.pop(k, None)
            else:
                self[k] = cur

    def add(self, other: "ResourceSet") -> None:
        for k, v in other.items():
            self[k] = self.get(k, 0.0) + v

    def copy(self) -> "ResourceSet":
        return ResourceSet.of(self)

    def utilization(self, total: "ResourceSet") -> float:
        """Max fractional utilization across resources present in `total`."""
        util = 0.0
        for k, cap in total.items():
            if cap > 0:
                used = cap - self.get(k, 0.0)
                util = max(util, used / cap)
        return util


def detect_node_resources(
    num_cpus: Optional[float] = None,
    num_tpus: Optional[int] = None,
    memory_bytes: Optional[int] = None,
    object_store_bytes: Optional[int] = None,
    custom: Optional[Dict[str, float]] = None,
) -> ResourceSet:
    """Detect this host's schedulable resources.

    TPU detection avoids initializing a jax backend (which would claim the
    chips): we trust explicit args, then the TPU_CHIPS / TPU topology env vars
    the TPU VM runtime sets, and only count; we never touch the devices.
    """
    rs = ResourceSet()
    rs[CPU] = float(num_cpus if num_cpus is not None else (os.cpu_count() or 1))
    if num_tpus is None:
        num_tpus = _detect_tpu_chips()
    if num_tpus:
        rs[TPU] = float(num_tpus)
    if memory_bytes is None:
        memory_bytes = _detect_memory()
    rs[MEMORY] = float(memory_bytes)
    if object_store_bytes:
        rs[OBJECT_STORE_MEMORY] = float(object_store_bytes)
    if custom:
        for k, v in custom.items():
            rs[k] = float(v)
    # TPU pod membership (GKE env / GCE metadata): accelerator-type label +
    # the slice-head gang resource on worker 0. Explicit custom resources win.
    if rs.get(TPU):
        from ray_tpu._private.accelerators import tpu_pod_resources

        for k, v in tpu_pod_resources().items():
            rs.setdefault(k, float(v))
    return rs


def _detect_tpu_chips() -> int:
    # TPU_VISIBLE_CHIPS-style isolation (reference accelerators/tpu.py:30).
    visible = os.environ.get("TPU_VISIBLE_CHIPS")
    if visible:
        return len([c for c in visible.split(",") if c.strip()])
    chips = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS")
    if chips:
        try:
            dims = [int(x) for x in chips.split(",")]
            n = 1
            for d in dims:
                n *= d
            return n
        except ValueError:
            pass
    if os.environ.get("RAY_TPU_FORCE_TPU_CHIPS"):
        return int(os.environ["RAY_TPU_FORCE_TPU_CHIPS"])
    # GKE sets the pod accelerator type but not per-host chip bounds:
    # derive chips/host from the topology (accelerators.py discovery)
    accel = os.environ.get("TPU_ACCELERATOR_TYPE")
    if accel:
        from ray_tpu._private.accelerators import (
            chips_from_accelerator_type)

        return chips_from_accelerator_type(accel)
    return 0


def _detect_memory() -> int:
    try:
        import psutil

        return int(psutil.virtual_memory().total)
    except Exception:
        return 8 * 1024**3


def pg_resource_name(pg_id_hex: str, bundle_index: int | None = None) -> str:
    """Formatted resource name for a placement-group bundle reservation.

    Mirrors the reference's `<name>_group_<index>_<pg_id>` convention so tasks
    scheduled into a bundle consume the reserved slice, not the free pool.
    """
    if bundle_index is None:
        return f"bundle_group_{pg_id_hex}"
    return f"bundle_group_{bundle_index}_{pg_id_hex}"
