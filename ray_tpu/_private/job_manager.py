"""Job submission: run driver entrypoints on the head node.

Analog of the reference's job manager
(`dashboard/modules/job/job_manager.py:405` JobManager.submit_job): an
entrypoint shell command runs as a subprocess on the controller's host
with RAY_TPU_ADDRESS pointing at this cluster, stdout/stderr captured to
a per-job log file, status tracked through PENDING/RUNNING/SUCCEEDED/
FAILED/STOPPED. The REST surface (/api/jobs) and the `ray_tpu.scripts.jobs`
CLI wrap this, mirroring `ray job submit/status/logs/stop`.
"""

from __future__ import annotations

import os
import signal
import subprocess
import time
import uuid
from typing import Any, Dict, List, Optional


class JobManager:
    def __init__(self, session_dir: str, cluster_address: str):
        self._dir = os.path.join(session_dir or ".", "job_logs")
        os.makedirs(self._dir, exist_ok=True)
        self._address = cluster_address
        self._jobs: Dict[str, Dict[str, Any]] = {}

    def submit(self, entrypoint: str,
               env_vars: Optional[Dict[str, str]] = None,
               submission_id: Optional[str] = None) -> str:
        job_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        if job_id in self._jobs:
            raise ValueError(f"submission_id {job_id!r} already exists")
        log_path = os.path.join(self._dir, f"{job_id}.log")
        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = self._address
        env.update(env_vars or {})
        log = open(log_path, "ab")
        proc = subprocess.Popen(
            ["/bin/bash", "-c", entrypoint],
            stdout=log, stderr=subprocess.STDOUT, env=env,
            start_new_session=True,  # signal the whole entrypoint group
        )
        log.close()
        self._jobs[job_id] = {
            "job_id": job_id,
            "entrypoint": entrypoint,
            "proc": proc,
            "log_path": log_path,
            "start_time": time.time(),
            "end_time": None,
            "status": "RUNNING",
        }
        return job_id

    def _refresh(self, rec: Dict[str, Any]) -> None:
        if rec["status"] != "RUNNING":
            return
        code = rec["proc"].poll()
        if code is None:
            return
        rec["end_time"] = time.time()
        rec["status"] = "SUCCEEDED" if code == 0 else "FAILED"
        rec["exit_code"] = code

    def status(self, job_id: str) -> Optional[Dict[str, Any]]:
        rec = self._jobs.get(job_id)
        if rec is None:
            return None
        self._refresh(rec)
        return {k: v for k, v in rec.items() if k != "proc"}

    def list(self) -> List[Dict[str, Any]]:
        for rec in self._jobs.values():
            self._refresh(rec)
        return [{k: v for k, v in r.items() if k != "proc"}
                for r in self._jobs.values()]

    def logs(self, job_id: str, tail_bytes: int = 1024 * 1024) -> str:
        rec = self._jobs.get(job_id)
        if rec is None:
            return ""
        try:
            with open(rec["log_path"], "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    def stop(self, job_id: str) -> bool:
        rec = self._jobs.get(job_id)
        if rec is None:
            return False
        self._refresh(rec)
        if rec["status"] != "RUNNING":
            return False
        try:
            os.killpg(os.getpgid(rec["proc"].pid), signal.SIGTERM)
        except Exception:
            try:
                rec["proc"].terminate()
            except Exception:
                pass
        try:
            rec["proc"].wait(timeout=10)
        except Exception:
            try:
                os.killpg(os.getpgid(rec["proc"].pid), signal.SIGKILL)
            except Exception:
                pass
        rec["status"] = "STOPPED"
        rec["end_time"] = time.time()
        return True
