"""Data iterators — the consumption boundary, including device ingest.

Analog of `ray.data.DataIterator` (`python/ray/data/iterator.py`) and the
stream-split iterator behind `streaming_split`
(`python/ray/data/_internal/iterator/stream_split_iterator.py`). The TPU
path is `iter_jax_batches`: numpy batches are pushed to device with
`jax.device_put` one batch AHEAD of the consumer (double buffering), so
host→HBM DMA for batch k+1 overlaps with the step computing on batch k —
the framework-level replacement for plasma zero-copy into device memory.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, batches_from_blocks, block_rows


class DataIterator:
    """Abstract: subclasses provide _block_iter()."""

    def _block_iter(self) -> Iterator[Block]:
        raise NotImplementedError

    def _block_iter_windowed(self, window: int) -> Iterator[Block]:
        """Block stream with up to ``window`` fetches bound ahead.
        Subclasses that resolve refs override this to keep a window of
        gets in flight; the base just streams (prefetching then happens
        only at the batch level)."""
        return self._block_iter()

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        prefetch_batches: int = 1,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
    ) -> Iterator[Any]:
        # prefetch overlaps at BOTH levels: block fetches are bound
        # ahead with a window (the consumer no longer eats a store
        # round-trip at every block boundary) and finished batches queue
        # through a background fill thread so a computing consumer finds
        # the next one ready
        if prefetch_batches and prefetch_batches > 0:
            blocks = self._block_iter_windowed(
                max(2, int(prefetch_batches)))
        else:
            blocks = self._block_iter()
        if local_shuffle_buffer_size:
            blocks = _shuffle_blocks(blocks, local_shuffle_buffer_size,
                                     local_shuffle_seed)
        batches = batches_from_blocks(blocks, batch_size, batch_format,
                                      drop_last)
        if prefetch_batches and prefetch_batches > 0:
            batches = _prefetch(batches, prefetch_batches)
        return batches

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self._block_iter():
            yield from block_rows(block)

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           dtypes=None, device: str = "cpu",
                           **kw) -> Iterator[Dict[str, Any]]:
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy", **kw):
            yield {
                k: torch.as_tensor(
                    v, dtype=(dtypes.get(k) if isinstance(dtypes, dict)
                              else dtypes), device=device)
                for k, v in batch.items()
            }

    def iter_jax_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        sharding=None,
        prefetch: int = 2,
        **kw,
    ) -> Iterator[Dict[str, Any]]:
        """Numpy batches → device arrays, `prefetch` batches ahead.

        ``sharding`` may be a `jax.sharding.Sharding` (applied to every
        array) or a dict column→Sharding. With a NamedSharding over a dp/sp
        mesh this is the data-ingest edge of an SPMD step: each host puts
        its shard, XLA assembles the global array.
        """
        import jax

        def put(batch):
            if sharding is None:
                return jax.tree.map(jax.numpy.asarray, batch)
            if isinstance(sharding, dict):
                return {k: jax.device_put(v, sharding.get(k)) for k, v in
                        batch.items()}
            return {k: jax.device_put(v, sharding) for k, v in batch.items()}

        host = self.iter_batches(batch_size=batch_size,
                                 batch_format="numpy",
                                 prefetch_batches=0, **kw)
        window: List[Any] = []
        for batch in host:
            window.append(put(batch))  # async dispatch: returns immediately
            if len(window) > max(1, prefetch):
                yield window.pop(0)
        yield from window

    def materialize(self):
        from ray_tpu.data.dataset import _input_dataset

        return _input_dataset(list(self._block_iter())).materialize()


def _prefetch(it: Iterator[Any], depth: int) -> Iterator[Any]:
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    _SENTINEL = object()
    err: List[BaseException] = []
    stop = threading.Event()

    def _put(x) -> bool:
        # bounded put that notices consumer abandonment — otherwise an
        # early-exiting consumer (take_batch, break in a train loop) leaks
        # this thread plus the upstream generator's in-flight window
        while not stop.is_set():
            try:
                q.put(x, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def fill():
        try:
            for x in it:
                if not _put(x):
                    return
        except BaseException as e:
            err.append(e)
        finally:
            _put(_SENTINEL)

    t = threading.Thread(target=fill, daemon=True)
    t.start()
    try:
        while True:
            x = q.get()
            if x is _SENTINEL:
                if err:
                    raise err[0]
                return
            yield x
    finally:
        stop.set()


def _shuffle_blocks(blocks: Iterator[Block], buffer_rows: int,
                    seed: Optional[int]) -> Iterator[Block]:
    """Windowed local shuffle (reference: local_shuffle_buffer_size)."""
    from ray_tpu.data.block import concat_blocks, slice_block

    rng = np.random.default_rng(seed)
    buf: List[Block] = []
    rows = 0
    for b in blocks:
        buf.append(b)
        rows += b.num_rows
        if rows >= buffer_rows:
            merged = concat_blocks(buf)
            merged = merged.take(rng.permutation(merged.num_rows))
            emit = slice_block(merged, 0, merged.num_rows // 2)
            keep = slice_block(merged, merged.num_rows // 2, merged.num_rows)
            yield emit
            buf, rows = [keep], keep.num_rows
    if buf:
        merged = concat_blocks(buf)
        if merged.num_rows:
            yield merged.take(rng.permutation(merged.num_rows))


class _BlockStreamIterator(DataIterator):
    """Iterates a Dataset's own streaming execution (driver-side)."""

    def __init__(self, ds):
        self._ds = ds

    def _block_iter(self) -> Iterator[Block]:
        for ref, _meta in self._ds._stream():
            yield ray_tpu.get(ref)

    def _block_iter_windowed(self, window: int) -> Iterator[Block]:
        """Bound-ahead block resolution: pull up to ``window`` refs from
        the task stream (which also drives task submission ahead) and
        resolve them in ONE batched get — the PR-2 batched-locate path
        (one store_locate_batch RPC per node per window instead of a
        locate round-trip per block). The per-block boundary stall the
        synchronous pull paid collapses into one amortized wait per
        window, hidden by the batch-level fill thread."""
        from collections import deque

        pend: deque = deque()
        for ref, _meta in self._ds._stream():
            pend.append(ref)
            if len(pend) >= window:
                for b in ray_tpu.get(list(pend)):
                    yield b
                pend.clear()
        if pend:
            yield from ray_tpu.get(list(pend))


class _SplitCoordinator:
    """Actor: runs ONE streaming execution, hands blocks to n consumers
    first-come-first-served (reference: SplitCoordinator in
    `stream_split_iterator.py`)."""

    def __init__(self, ops, concurrency, n: int = 1, equal: bool = False):
        from ray_tpu.data._internal.executor import execute_plan

        self._ops = ops
        self._concurrency = concurrency
        # generation = which pass over the dataset the current stream
        # serves; consumers name the pass they want, and a restart happens
        # only when EVERY rank has moved past the drained generation (so a
        # late-starting rank's first pass still reads the original stream).
        self._generation = 1
        self._rank_epochs: Dict[int, int] = {}
        self._gen = execute_plan(ops, concurrency)
        self._done = False
        self._equal = equal
        self._n = n
        # equal mode: blocks are dealt round-robin by arrival index so every
        # consumer sees the same block count (±1) — lockstep SPMD loops with
        # per-batch collectives need matching iteration counts.
        self._buffers: Dict[int, List[Any]] = {i: [] for i in range(n)}
        # Handed-out refs are pinned here (as (ref, generation)) until
        # the consumer acks having read the block — returning a ref from
        # an actor method drops the actor's local reference, and without
        # the pin the owner could GC the block before the consumer's get
        # lands. The generation tag lets requeue() drop a stale return.
        self._pinned = {}
        # Blocks a consumer handed back unread (a prefetch lookahead
        # abandoned on early exit): served to the next requester before
        # the stream is pulled, so sibling ranks' epoch stays complete.
        # Cleared on epoch restart — the fresh execution re-reads every
        # block, so serving a stale one would duplicate its rows.
        self._returned: List[Any] = []
        self._deal_idx = 0  # arrival index for equal-mode round-robin
        self._next_token = 0

    def next_block_ref(self, rank: int = 0, epoch: int = 1):
        # Re-iterable shards (reference: StreamSplitDataIterator re-executes
        # per epoch): restart the execution for epoch e only once the
        # current stream is drained and all n ranks have asked for >= e.
        self._rank_epochs[rank % self._n] = epoch
        if (epoch > self._generation and self._done
                and not any(self._buffers.values())
                and len(self._rank_epochs) == self._n
                and all(e >= epoch for e in self._rank_epochs.values())):
            from ray_tpu.data._internal.executor import execute_plan

            self._generation = epoch
            self._gen = execute_plan(self._ops, self._concurrency)
            self._done = False
            # stranded returns belong to the superseded pass (every rank
            # moved on, nobody will drain them) and the fresh execution
            # re-produces those blocks — keeping them would either hang
            # the restart or duplicate their rows into this epoch
            self._returned.clear()
        if epoch > self._generation:
            # stream for this epoch not open yet (other ranks still on the
            # previous pass) — caller polls again
            return "PENDING"
        ref = None
        if self._returned:
            ref = self._returned.pop(0)
        elif self._equal:
            buf = self._buffers[rank % self._n]
            while not buf and not self._done:
                try:
                    r, _meta = next(self._gen)
                    self._buffers[self._deal_idx % self._n].append(r)
                    self._deal_idx += 1
                except StopIteration:
                    self._done = True
            if buf:
                ref = buf.pop(0)
        else:
            if not self._done:
                try:
                    ref, _meta = next(self._gen)
                except StopIteration:
                    self._done = True
        if ref is None:
            return None
        token = self._next_token
        self._next_token += 1
        self._pinned[token] = (ref, self._generation)
        return token, ref

    def release(self, token: int) -> None:
        self._pinned.pop(token, None)

    def requeue(self, token: int) -> None:
        """Hand an UNREAD block back (an abandoned prefetch lookahead):
        it goes to the front of the stream for the next requester —
        release() would silently drop its rows from the epoch. A return
        landing after the stream restarted for a newer epoch is DROPPED:
        the new execution re-reads that block, so serving the stale one
        would duplicate its rows."""
        entry = self._pinned.pop(token, None)
        if entry is not None and entry[1] == self._generation:
            self._returned.append(entry[0])


class _ExchangeSplitIterator(DataIterator):
    """One rank of a streaming-split over the all-to-all exchange
    (`data/_internal/exchange.py`): this iterator reads its own
    consumer's output channel — deterministic partition-assigned
    splits (rank c gets exactly the rows
    ``exchange_assignments(...) == c``, exact vs the task baseline at
    the same seed), where the coordinator-fed split is
    first-come-first-served. Each ``iter_batches``/``_block_iter`` call
    consumes the NEXT epoch of the shared executor (built with
    ``epochs=``); ``close()`` tears the whole mesh down for every rank
    (the executor is shared)."""

    def __init__(self, executor, rank: int):
        self._ex = executor
        self._rank = rank

    @property
    def executor(self):
        return self._ex

    def _block_iter(self) -> Iterator[Block]:
        from ray_tpu.data.block import batch_to_block

        for b in self._ex.rank_epoch(self._rank):
            yield batch_to_block(b)

    def stats(self) -> List[dict]:
        return self._ex.rank_epoch_stats(self._rank)

    def close(self) -> None:
        self._ex.shutdown()


class _StreamSplitIterator(DataIterator):
    def __init__(self, coordinator, rank: int):
        self._coord = coordinator
        self._rank = rank
        self._epoch = 0

    def _block_iter(self) -> Iterator[Block]:
        import time as _time

        self._epoch += 1
        epoch = self._epoch
        while True:
            out = ray_tpu.get(
                self._coord.next_block_ref.remote(self._rank, epoch))
            if out is None:
                return
            if out == "PENDING":
                _time.sleep(0.02)
                continue
            token, ref = out
            block = ray_tpu.get(ref)
            self._coord.release.remote(token)  # fire-and-forget unpin
            yield block

    def _block_iter_windowed(self, window: int) -> Iterator[Block]:
        """One-ahead pipelining of the coordinator round-trip: the NEXT
        block assignment is requested before the current block is
        fetched, so the two serial RPCs the synchronous pull paid per
        block (assignment + get) overlap with the consumer. Assignment
        order is unchanged — this rank just holds one extra block, which
        is drained (and its pin released) if the consumer stops early."""
        import time as _time

        self._epoch += 1
        epoch = self._epoch
        fut = self._coord.next_block_ref.remote(self._rank, epoch)
        try:
            while True:
                out = ray_tpu.get(fut)
                fut = None
                if out is None:
                    return
                if out == "PENDING":
                    _time.sleep(0.02)
                    fut = self._coord.next_block_ref.remote(
                        self._rank, epoch)
                    continue
                token, ref = out
                fut = self._coord.next_block_ref.remote(self._rank, epoch)
                block = ray_tpu.get(ref)
                self._coord.release.remote(token)  # fire-and-forget unpin
                yield block
        finally:
            if fut is not None:
                # drain the lookahead so an early-exiting consumer never
                # strands its assigned block: requeue hands the UNREAD
                # block back to the coordinator for a sibling rank
                # (release would silently shrink the shared epoch)
                try:
                    out = ray_tpu.get(fut, timeout=30)
                    if isinstance(out, tuple):
                        self._coord.requeue.remote(out[0])
                except Exception:
                    pass
