"""Read tasks and write functions for the built-in formats.

Analog of the reference's `python/ray/data/datasource/` (parquet, csv,
json, numpy, range, binary sources and the corresponding datasinks). A
read task is a zero-arg callable returning one Block, executed remotely by
the streaming executor's ReadStage.
"""

from __future__ import annotations

import glob
import os
from typing import Callable, List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import Block, batch_to_block, even_cuts


def _expand_paths(paths, suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        p = os.path.expanduser(p)
        if os.path.isdir(p):
            pattern = os.path.join(p, "**", f"*{suffix or ''}")
            files.extend(f for f in glob.glob(pattern, recursive=True)
                         if os.path.isfile(f))
        elif any(ch in p for ch in "*?["):
            files.extend(f for f in glob.glob(p) if os.path.isfile(f))
        elif os.path.isfile(p):
            files.append(p)
        else:
            raise FileNotFoundError(p)
    if not files:
        raise FileNotFoundError(f"no files matched {paths!r}")
    return sorted(files)


# ------------------------------------------------------------- read tasks


def range_tasks(n: int, parallelism: int) -> List[Callable[[], Block]]:
    cuts = even_cuts(n, parallelism)

    def make(lo: int, hi: int):
        return lambda: pa.table({"id": np.arange(lo, hi, dtype=np.int64)})

    return [make(cuts[i], cuts[i + 1]) for i in range(len(cuts) - 1)]


def range_tensor_tasks(n: int, shape, parallelism: int):
    cuts = even_cuts(n, parallelism)

    def make(lo: int, hi: int):
        def task():
            count = hi - lo
            base = np.arange(lo, hi, dtype=np.int64).reshape(
                (count,) + (1,) * len(shape))
            data = np.broadcast_to(base, (count,) + tuple(shape)).copy()
            return batch_to_block({"data": data})

        return task

    return [make(cuts[i], cuts[i + 1]) for i in range(len(cuts) - 1)]


def parquet_tasks(paths, columns=None) -> List[Callable[[], Block]]:
    files = _expand_paths(paths, ".parquet")

    def make(f):
        def task():
            import pyarrow.parquet as pq

            return pq.read_table(f, columns=columns)

        return task

    return [make(f) for f in files]


def csv_tasks(paths) -> List[Callable[[], Block]]:
    files = _expand_paths(paths, ".csv")

    def make(f):
        def task():
            import pyarrow.csv as pacsv

            return pacsv.read_csv(f)

        return task

    return [make(f) for f in files]


def json_tasks(paths) -> List[Callable[[], Block]]:
    files = _expand_paths(paths)

    def make(f):
        def task():
            import pyarrow.json as pajson

            return pajson.read_json(f)

        return task

    return [make(f) for f in files]


def numpy_tasks(paths) -> List[Callable[[], Block]]:
    files = _expand_paths(paths, ".npy")

    def make(f):
        def task():
            return batch_to_block({"data": np.load(f)})

        return task

    return [make(f) for f in files]


def binary_tasks(paths) -> List[Callable[[], Block]]:
    files = _expand_paths(paths)

    def make(f):
        def task():
            with open(f, "rb") as fh:
                payload = fh.read()
            return pa.table({"path": [f], "bytes": pa.array([payload],
                                                            pa.binary())})

        return task

    return [make(f) for f in files]


# ------------------------------------------------------------ write tasks


def write_block(block: Block, path: str, index: int, fmt: str) -> str:
    os.makedirs(path, exist_ok=True)
    f = os.path.join(path, f"{index:06d}.{fmt}")
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(block, f)
    elif fmt == "csv":
        import pyarrow.csv as pacsv

        pacsv.write_csv(block, f)
    elif fmt == "json":
        block.to_pandas().to_json(f, orient="records", lines=True)
    else:
        raise ValueError(f"unknown write format {fmt}")
    return f
