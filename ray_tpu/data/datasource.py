"""Read tasks and write functions for the built-in formats.

Analog of the reference's `python/ray/data/datasource/` (parquet, csv,
json, numpy, range, binary sources and the corresponding datasinks). A
read task is a zero-arg callable returning one Block, executed remotely by
the streaming executor's ReadStage.
"""

from __future__ import annotations

import glob
import os
from typing import Callable, List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import Block, batch_to_block, even_cuts


def _expand_paths(paths, suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        p = os.path.expanduser(p)
        if os.path.isdir(p):
            pattern = os.path.join(p, "**", f"*{suffix or ''}")
            files.extend(f for f in glob.glob(pattern, recursive=True)
                         if os.path.isfile(f))
        elif any(ch in p for ch in "*?["):
            files.extend(f for f in glob.glob(p) if os.path.isfile(f))
        elif os.path.isfile(p):
            files.append(p)
        else:
            raise FileNotFoundError(p)
    if not files:
        raise FileNotFoundError(f"no files matched {paths!r}")
    return sorted(files)


# ------------------------------------------------------------- read tasks


def range_tasks(n: int, parallelism: int) -> List[Callable[[], Block]]:
    cuts = even_cuts(n, parallelism)

    def make(lo: int, hi: int):
        return lambda: pa.table({"id": np.arange(lo, hi, dtype=np.int64)})

    return [make(cuts[i], cuts[i + 1]) for i in range(len(cuts) - 1)]


def range_tensor_tasks(n: int, shape, parallelism: int):
    cuts = even_cuts(n, parallelism)

    def make(lo: int, hi: int):
        def task():
            count = hi - lo
            base = np.arange(lo, hi, dtype=np.int64).reshape(
                (count,) + (1,) * len(shape))
            data = np.broadcast_to(base, (count,) + tuple(shape)).copy()
            return batch_to_block({"data": data})

        return task

    return [make(cuts[i], cuts[i + 1]) for i in range(len(cuts) - 1)]


def parquet_tasks(paths, columns=None) -> List[Callable[[], Block]]:
    files = _expand_paths(paths, ".parquet")

    def make(f):
        def task():
            import pyarrow.parquet as pq

            return pq.read_table(f, columns=columns)

        return task

    return [make(f) for f in files]


def csv_tasks(paths) -> List[Callable[[], Block]]:
    files = _expand_paths(paths, ".csv")

    def make(f):
        def task():
            import pyarrow.csv as pacsv

            return pacsv.read_csv(f)

        return task

    return [make(f) for f in files]


def json_tasks(paths) -> List[Callable[[], Block]]:
    files = _expand_paths(paths)

    def make(f):
        def task():
            import pyarrow.json as pajson

            return pajson.read_json(f)

        return task

    return [make(f) for f in files]


def numpy_tasks(paths) -> List[Callable[[], Block]]:
    files = _expand_paths(paths, ".npy")

    def make(f):
        def task():
            return batch_to_block({"data": np.load(f)})

        return task

    return [make(f) for f in files]


def binary_tasks(paths) -> List[Callable[[], Block]]:
    files = _expand_paths(paths)

    def make(f):
        def task():
            with open(f, "rb") as fh:
                payload = fh.read()
            return pa.table({"path": [f], "bytes": pa.array([payload],
                                                            pa.binary())})

        return task

    return [make(f) for f in files]


# ------------------------------------------------------------ write tasks


def write_block(block: Block, path: str, index: int, fmt: str) -> str:
    os.makedirs(path, exist_ok=True)
    f = os.path.join(path, f"{index:06d}.{fmt}")
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(block, f)
    elif fmt == "csv":
        import pyarrow.csv as pacsv

        pacsv.write_csv(block, f)
    elif fmt == "json":
        block.to_pandas().to_json(f, orient="records", lines=True)
    elif fmt == "tfrecords":
        return write_tfrecords_block(block, path, index)
    else:
        raise ValueError(f"unknown write format {fmt}")
    return f


# ------------------------------------------------- tfrecord (pure python)
# Wire format (tensorflow/core/lib/io/record_writer.cc):
#   [u64 length][u32 masked_crc32c(length)][data][u32 masked_crc32c(data)]
# Payloads are tf.train.Example protos; a minimal protobuf wire parser below
# decodes bytes_list/float_list/int64_list features without the protobuf
# runtime (the environment does not pin tensorflow).

def _masked_crc(data: bytes) -> int:
    # native slice-by-8 CRC32C when the toolchain is present (MB-scale
    # payload checksums are the write path's hot loop), python otherwise
    from ray_tpu._native.codec import masked_crc32c

    return masked_crc32c(data)


def _read_varint(buf: bytes, pos: int):
    # shared primitive (native codec's python fallback) — one copy
    from ray_tpu._native.codec import _py_read_varint

    return _py_read_varint(buf, pos)


def _parse_proto_fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a proto message."""
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:          # varint
            val, pos = _read_varint(buf, pos)
        elif wire == 1:        # 64-bit
            val, pos = buf[pos:pos + 8], pos + 8
        elif wire == 2:        # length-delimited
            n, pos = _read_varint(buf, pos)
            val, pos = buf[pos:pos + n], pos + n
        elif wire == 5:        # 32-bit
            val, pos = buf[pos:pos + 4], pos + 4
        else:
            raise ValueError(f"unsupported proto wire type {wire}")
        yield field, wire, val


def _to_int64(x: int) -> int:
    return x - (1 << 64) if x >= 1 << 63 else x


def _parse_example(buf: bytes):
    """tf.train.Example -> {name: list}. Example{features=1} ->
    Features{feature=1 map<string,Feature>} -> Feature{bytes_list=1,
    float_list=2, int64_list=3}, each a packed/repeated list field 1."""
    import struct as _struct

    out = {}
    for f, _, features in _parse_proto_fields(buf):
        if f != 1:
            continue
        for ff, _, entry in _parse_proto_fields(features):
            if ff != 1:
                continue
            name, feature = None, b""
            for ef, _, v in _parse_proto_fields(entry):
                if ef == 1:
                    name = v.decode()
                elif ef == 2:
                    feature = v
            if name is None:
                continue
            values: List = []
            for tf_, _, lst in _parse_proto_fields(feature):
                for lf, lw, lv in _parse_proto_fields(lst):
                    if lf != 1:
                        continue
                    if tf_ == 1:                  # bytes_list
                        values.append(lv)
                    elif tf_ == 2:                # float_list
                        if lw == 2:               # packed
                            values.extend(_struct.unpack(
                                f"<{len(lv) // 4}f", lv))
                        else:
                            values.append(_struct.unpack("<f", lv)[0])
                    elif tf_ == 3:                # int64_list
                        if lw == 2:
                            from ray_tpu._native.codec import varint_decode

                            values.extend(varint_decode(lv))
                        else:
                            values.append(_to_int64(lv))
            out[name] = values
    return out


def iter_tfrecords(path: str):
    """Yield raw record payloads from one TFRecord file (CRCs skipped on
    read, verified lengths only)."""
    import struct as _struct

    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                return
            (length,), _crc = _struct.unpack("<Q", header[:8]), header[8:]
            data = f.read(length)
            f.read(4)  # data crc
            if len(data) < length:
                return
            yield data


def tfrecord_tasks(paths) -> List[Callable[[], Block]]:
    files = _expand_paths(paths)

    def make(f):
        def task():
            rows = []
            for payload in iter_tfrecords(f):
                ex = _parse_example(payload)
                row = {}
                for k, vals in ex.items():
                    row[k] = vals[0] if len(vals) == 1 else vals
                rows.append(row)
            if not rows:
                return pa.table({})
            keys = sorted({k for r in rows for k in r})
            return batch_to_block({k: [r.get(k) for r in rows]
                                   for k in keys})

        return task

    return [make(f) for f in files]


def _encode_varint(x: int) -> bytes:
    from ray_tpu._native.codec import _py_encode_varint

    return _py_encode_varint(x)


def _encode_field(field: int, wire: int, payload: bytes) -> bytes:
    return _encode_varint((field << 3) | wire) + payload


def _encode_example(row: Dict[str, Any]) -> bytes:
    """Encode one row-dict as a tf.train.Example proto."""
    import struct as _struct

    entries = b""
    for name, value in row.items():
        if value is None:
            continue  # sparse row: missing feature, matches reader semantics
        vals = value if isinstance(value, (list, tuple, np.ndarray)) else [
            value]
        if len(vals) and isinstance(vals[0], (bytes, str)):
            items = b"".join(
                _encode_field(1, 2, _encode_varint(len(v)) + v)
                for v in ((x.encode() if isinstance(x, str) else x)
                          for x in vals))
            feature = _encode_field(1, 2, _encode_varint(len(items)) + items)
        elif len(vals) and isinstance(vals[0], (float, np.floating)):
            packed = _struct.pack(f"<{len(vals)}f", *[float(v)
                                                      for v in vals])
            lst = _encode_field(1, 2, _encode_varint(len(packed)) + packed)
            feature = _encode_field(2, 2, _encode_varint(len(lst)) + lst)
        else:
            from ray_tpu._native.codec import varint_encode

            packed = varint_encode([int(v) for v in vals])
            lst = _encode_field(1, 2, _encode_varint(len(packed)) + packed)
            feature = _encode_field(3, 2, _encode_varint(len(lst)) + lst)
        entry = (_encode_field(1, 2, _encode_varint(len(name.encode()))
                               + name.encode())
                 + _encode_field(2, 2, _encode_varint(len(feature))
                                 + feature))
        entries += _encode_field(1, 2, _encode_varint(len(entry)) + entry)
    return _encode_field(1, 2, _encode_varint(len(entries)) + entries)


def write_tfrecords_block(block: Block, path: str, index: int) -> str:
    import struct as _struct

    os.makedirs(path, exist_ok=True)
    f = os.path.join(path, f"{index:06d}.tfrecords")
    rows = block.to_pylist()
    with open(f, "wb") as fh:
        for row in rows:
            payload = _encode_example(row)
            header = _struct.pack("<Q", len(payload))
            fh.write(header)
            fh.write(_struct.pack("<I", _masked_crc(header)))
            fh.write(payload)
            fh.write(_struct.pack("<I", _masked_crc(payload)))
    return f


# ------------------------------------------------------------------ images


def image_tasks(paths, size=None, mode: Optional[str] = None
                ) -> List[Callable[[], Block]]:
    """One block per file; columns: image ([H,W,C] nested list), path.
    `size=(h, w)` resizes (required when mixing image sizes into one
    batch); `mode` forces a PIL conversion (e.g. "RGB", "L")."""
    exts = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")
    files = [f for f in _expand_paths(paths)
             if f.lower().endswith(exts)]
    if not files:
        raise FileNotFoundError(f"no image files in {paths!r}")

    def make(f):
        def task():
            from PIL import Image

            img = Image.open(f)
            if mode:
                img = img.convert(mode)
            if size is not None:
                img = img.resize((size[1], size[0]))
            arr = np.asarray(img)
            return batch_to_block({"image": arr[None], "path": [f]})

        return task

    return [make(f) for f in files]


# ------------------------------------------------------------- webdataset


def webdataset_tasks(paths) -> List[Callable[[], Block]]:
    """WebDataset-style tar shards: members grouped by basename stem form
    one sample; columns are the extensions ("jpg" decoded to arrays, "txt"/
    "cls" to str/int, "json" parsed, anything else raw bytes)."""
    files = _expand_paths(paths, ".tar")

    def _decode(ext: str, payload: bytes):
        if ext in ("jpg", "jpeg", "png", "bmp", "webp"):
            import io as _io

            from PIL import Image

            return np.asarray(Image.open(_io.BytesIO(payload)))
        if ext in ("txt", "text"):
            return payload.decode()
        if ext == "cls":
            return int(payload.decode().strip())
        if ext == "json":
            import json as _json

            return _json.loads(payload.decode())
        return payload

    def make(f):
        def task():
            import tarfile

            samples: Dict[str, Dict[str, Any]] = {}
            order: List[str] = []
            with tarfile.open(f) as tar:
                for m in tar.getmembers():
                    if not m.isfile():
                        continue
                    base = os.path.basename(m.name)
                    stem, _, ext = base.partition(".")
                    if stem not in samples:
                        samples[stem] = {"__key__": stem}
                        order.append(stem)
                    payload = tar.extractfile(m).read()
                    samples[stem][ext.lower()] = _decode(
                        ext.lower(), payload)
            rows = [samples[k] for k in order]
            keys = sorted({k for r in rows for k in r})
            return batch_to_block({k: [r.get(k) for r in rows]
                                   for k in keys})

        return task

    return [make(f) for f in files]


# ------------------------------------------------------------------- sql


def _cursor_block(conn, sql: str) -> Block:
    try:
        cur = conn.cursor()
        # no params argument: passing one (even empty) makes
        # format/pyformat drivers (psycopg2, MySQLdb) interpret every
        # '%' in the SQL as a placeholder
        cur.execute(sql)
        names = [d[0] for d in cur.description]
        rows = cur.fetchall()
    finally:
        conn.close()
    if not rows:
        return pa.table({n: [] for n in names})
    cols = {n: [r[i] for r in rows] for i, n in enumerate(names)}
    return batch_to_block(cols)


def sql_tasks(sql: str, connection_factory: Callable[[], Any],
              partition_column: Optional[str] = None,
              lower_bound=None, upper_bound=None,
              parallelism: int = 1) -> List[Callable[[], Block]]:
    """DBAPI-2 source (reference `read_sql`). One task runs the whole
    query; with `partition_column` + NUMERIC bounds the read fans out
    into `parallelism` range-partitioned queries (Spark-JDBC recipe:
    bounds set the STRIDES only — the first partition is unbounded
    below and also takes NULLs, the last unbounded above, so no row is
    ever filtered out by the bounds). Literal numeric bounds are
    inlined because DBAPI paramstyles differ per driver.
    `connection_factory` must be picklable
    (e.g. `lambda: sqlite3.connect(path)`)."""
    if partition_column is None or parallelism <= 1:
        return [lambda: _cursor_block(connection_factory(), sql)]
    if lower_bound is None or upper_bound is None:
        raise ValueError(
            "partitioned read_sql needs lower_bound and upper_bound for "
            "the partition column")
    lo_b, hi_b = float(lower_bound), float(upper_bound)  # numeric only
    span = (hi_b - lo_b) / parallelism
    col = partition_column
    tasks: List[Callable[[], Block]] = []
    for i in range(parallelism):
        if i == 0 and i == parallelism - 1:
            where = "1=1"
        elif i == 0:
            where = f"({col} < {lo_b + span} OR {col} IS NULL)"
        elif i == parallelism - 1:
            where = f"{col} >= {lo_b + i * span}"
        else:
            where = (f"{col} >= {lo_b + i * span} AND "
                     f"{col} < {lo_b + (i + 1) * span}")

        def make(where=where):
            part_sql = f"SELECT * FROM ({sql}) __rt_sub WHERE {where}"
            return lambda: _cursor_block(connection_factory(), part_sql)

        tasks.append(make())
    return tasks


def orc_tasks(paths) -> List[Callable[[], Block]]:
    """ORC files via pyarrow.orc (reference `read_orc`): one task per
    file."""
    files = _expand_paths(paths, ".orc")

    def make(f):
        def task():
            from pyarrow import orc

            return orc.read_table(f)

        return task

    return [make(f) for f in files]


def mongo_tasks(uri: str, database: str, collection: str,
                pipeline: Optional[list] = None, parallelism: int = 4,
                client_factory: Optional[Callable[[], Any]] = None
                ) -> List[Callable[[], Block]]:
    """MongoDB source (ref
    `python/ray/data/datasource/mongo_datasource.py`): the collection is
    range-partitioned on `_id` into `parallelism` cursor reads, each an
    independent task. `client_factory` is the injection seam (production
    default: pymongo.MongoClient, gated on the library)."""
    if client_factory is None:
        def client_factory():  # noqa: F811 — production default
            try:
                import pymongo
            except ImportError as e:
                raise ImportError(
                    "read_mongo requires pymongo (not installed in this "
                    "image); pass client_factory= for a custom client"
                ) from e
            return pymongo.MongoClient(uri)

    # compute the stride ONCE at dataset construction and bake it into
    # every task: tasks executing at different times would otherwise
    # derive different strides from a drifting estimate and silently
    # drop/duplicate the rows between the two page grids. The estimate
    # only sets page BOUNDARIES — the last partition is unbounded, so a
    # stale count skews balance, never correctness.
    n = client_factory()[database][collection].estimated_document_count()
    per = max(1, -(-n // parallelism))  # ceil

    # skip/limit paging is only deterministic over a total order. Sorting
    # the collection scan on `_id` BEFORE the user pipeline gives every
    # task the same order through order-preserving stages ($match,
    # $project, $unwind, ...). Stages below DESTROY that order ($group
    # emits groups in unspecified per-run order; a user $sort rarely
    # totals), so the page grid must be re-sorted AFTER the pipeline —
    # which needs `_id` in the output ($group always emits one; raise if
    # a later stage provably drops it rather than silently drop/duplicate
    # the rows between adjacent partitions' pages).
    _ORDER_DESTROYING = {"$group", "$sort", "$sample", "$bucket",
                         "$bucketAuto", "$sortByCount", "$facet",
                         "$unionWith"}

    def _stage_name(stage) -> str:
        return next(iter(stage)) if isinstance(stage, dict) and stage else ""

    def _drops_id(stage) -> bool:
        name = _stage_name(stage)
        if not name:
            return False
        body = stage[name]
        if name == "$project" and isinstance(body, dict):
            return body.get("_id") in (0, False)
        if name == "$unset":
            fields = body if isinstance(body, list) else [body]
            return "_id" in fields
        return name in ("$replaceRoot", "$replaceWith")

    user_stages = list(pipeline or [])
    needs_resort = any(
        _stage_name(s) in _ORDER_DESTROYING for s in user_stages)
    if needs_resort and any(_drops_id(s) for s in user_stages):
        raise ValueError(
            "read_mongo: the pipeline reorders documents (e.g. $group/"
            "$sort) and then drops `_id`, so parallel skip/limit paging "
            "has no deterministic order to page over; keep `_id` in the "
            "output or read with parallelism=1")

    def part_task(index: int):
        def task():
            client = client_factory()
            coll = client[database][collection]
            start = index * per
            stages = ([{"$sort": {"_id": 1}}]
                      + user_stages
                      + ([{"$sort": {"_id": 1}}] if needs_resort else [])
                      + [{"$skip": start}])
            if index < parallelism - 1:
                stages.append({"$limit": per})
            rows = list(coll.aggregate(stages))
            for r in rows:
                r.pop("_id", None)  # ObjectIds aren't arrow-serializable
            if not rows:
                return pa.table({})
            keys = sorted({k for r in rows for k in r})  # union schema
            cols = {k: [r.get(k) for r in rows] for k in keys}
            return batch_to_block(cols)

        return task

    return [part_task(i) for i in range(parallelism)]


def bigquery_tasks(project_id: str, dataset: Optional[str] = None,
                   query: Optional[str] = None, parallelism: int = 4,
                   client_factory: Optional[Callable[[], Any]] = None
                   ) -> List[Callable[[], Block]]:
    """Cloud-warehouse source (ref
    `python/ray/data/datasource/bigquery_datasource.py`): `query` runs a
    BigQuery job whose destination table is then read page-parallel;
    bare `dataset` ("ds.table") reads the table directly. One read task
    per row-range stream, mirroring the reference's BigQuery Storage
    read sessions.

    `client_factory` is the injection seam (tests drive the exact call
    surface with a fake; production defaults to
    `google.cloud.bigquery.Client`, gated on the library)."""
    if (dataset is None) == (query is None):
        raise ValueError("read_bigquery needs exactly one of "
                         "dataset='ds.table' or query=...")

    if client_factory is None:
        def client_factory():  # noqa: F811 — production default
            try:
                from google.cloud import bigquery
            except ImportError as e:
                raise ImportError(
                    "read_bigquery requires google-cloud-bigquery (not "
                    "installed in this image); pass client_factory= to "
                    "use a custom client") from e
            return bigquery.Client(project=project_id)

    # the query job runs ONCE at dataset construction (one job, one
    # quota hit) and every stream task reads the SAME destination
    # table — per-task execution would run N jobs and, for
    # non-deterministic queries, page over N different result sets
    # (duplicated + missing rows). num_rows is resolved here too so
    # every task pages over one fixed grid.
    setup = client_factory()
    if query is not None:
        job = setup.query(query)
        job.result()  # wait; the anonymous destination holds the rows
        table = job.destination
    else:
        table = dataset
    n_rows = setup.get_table(table).num_rows
    per = max(1, -(-n_rows // parallelism))  # ceil

    def stream_task(index: int):
        def task():
            client = client_factory()
            start = index * per
            if start >= n_rows and index > 0:
                return pa.table({})
            rows = client.list_rows(table, start_index=start,
                                    max_results=per)
            arrow = rows.to_arrow()
            return arrow if arrow.num_rows or index == 0 else pa.table({})

        return task

    return [stream_task(i) for i in range(parallelism)]
