"""The Dataset API.

Analog of `ray.data.Dataset` (`python/ray/data/dataset.py:137`;
map_batches `:371`, iter_batches `:3641`, materialize `:4521`): a lazy
logical plan over distributed Arrow blocks, executed by the streaming
executor on the task layer. TPU angle: `iter_batches` composes with
`DataIterator.iter_jax_batches` (double-buffered `jax.device_put`) so
ingest overlaps with device compute — the plasma-zero-copy role is played
by host Arrow blocks + async device transfer (SURVEY §5 backend note).
"""

from __future__ import annotations

import builtins
import logging
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union as TUnion

import numpy as np

import dataclasses

import ray_tpu
from ray_tpu.data._internal import logical as L
from ray_tpu.data._internal.executor import (DEFAULT_CONCURRENCY,
                                             execute_plan, resolve_meta)
from ray_tpu.data.block import (Block, batch_to_block, block_meta,
                                block_rows, block_to_batch, even_cuts)
from ray_tpu.data.iterator import DataIterator, _BlockStreamIterator

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class ActorPoolStrategy:
    """Stateful-UDF compute strategy (≈ ray.data.ActorPoolStrategy):
    class UDFs run on a FIXED pool of long-lived actors sized `size`,
    falling back to `max_size` then `min_size` (accepted for API parity;
    this pool does not autoscale between min and max)."""

    size: Optional[int] = None
    min_size: Optional[int] = None
    max_size: Optional[int] = None

    @property
    def pool_size(self) -> Optional[int]:
        """None when the strategy doesn't specify a size — map_batches
        then falls through to its `concurrency` argument."""
        if self.size or self.max_size or self.min_size:
            return int(self.size or self.max_size or self.min_size)
        return None


class Dataset:
    def __init__(self, ops: List[L.LogicalOp],
                 concurrency: int = DEFAULT_CONCURRENCY):
        self._ops = ops
        self._concurrency = concurrency

    # ------------------------------------------------------------ plumbing

    def _with(self, op: L.LogicalOp) -> "Dataset":
        return Dataset(self._ops + [op], self._concurrency)

    def _stream(self):
        return execute_plan(self._ops, self._concurrency)

    def iter_internal_ref_bundles(self):
        """Public-ish escape hatch (reference: Dataset.iter_internal_ref_bundles)."""
        return self._stream()

    # ---------------------------------------------------------- transforms

    def map_batches(
        self,
        fn: Callable,
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        fn_args: Tuple = (),
        fn_kwargs: Optional[Dict] = None,
        compute: Optional["ActorPoolStrategy"] = None,
        concurrency: Optional[int] = None,
        num_cpus: Optional[float] = None,
        fn_constructor_args: Tuple = (),
        fn_constructor_kwargs: Optional[Dict] = None,
    ) -> "Dataset":
        """Map a UDF over batches.

        A class UDF (or compute=ActorPoolStrategy) runs on a pool of
        long-lived actors — the constructor runs once per actor, the
        stateful instance maps every batch (model-inference pattern).
        `concurrency` bounds in-flight tasks for function UDFs, or sets
        the pool size for class UDFs.
        """
        import inspect

        is_class_udf = inspect.isclass(fn)
        if compute is not None and not isinstance(compute, ActorPoolStrategy):
            raise TypeError(
                f"compute must be ActorPoolStrategy, got {compute!r}")
        if is_class_udf or compute is not None:
            if not is_class_udf:
                raise TypeError(
                    "compute=ActorPoolStrategy requires a class UDF")
            size = (compute.pool_size if compute is not None else None) \
                or concurrency or 2
            return self._with(L.ActorPoolMap(
                fn_cls=fn,
                fn_constructor_args=tuple(fn_constructor_args),
                fn_constructor_kwargs=dict(fn_constructor_kwargs or {}),
                batch_size=batch_size,
                batch_format=batch_format,
                fn_args=tuple(fn_args),
                fn_kwargs=dict(fn_kwargs or {}),
                pool_size=int(size),
                num_cpus=float(num_cpus if num_cpus is not None else 1.0),
                label=getattr(fn, "__name__", "actor_map")))
        if fn_constructor_args or fn_constructor_kwargs:
            raise TypeError(
                "fn_constructor_args/kwargs only apply to class UDFs")
        return self._with(L.OneToOne(
            L.make_map_batches_transform(fn, batch_size, batch_format,
                                         fn_args, fn_kwargs),
            label=getattr(fn, "__name__", "map_batches"),
            concurrency=concurrency,
            num_cpus=num_cpus))

    def map(self, fn: Callable) -> "Dataset":
        return self._with(L.OneToOne(L.make_map_rows_transform(fn),
                                     label="map"))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._with(L.OneToOne(L.make_flat_map_transform(fn),
                                     label="flat_map"))

    def filter(self, fn: Callable) -> "Dataset":
        return self._with(L.OneToOne(L.make_filter_transform(fn),
                                     label="filter"))

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        return self._with(L.OneToOne(L.make_add_column_transform(name, fn),
                                     label=f"add_column({name})"))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(
            lambda b: b.drop_columns(cols), batch_format="pyarrow")

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(
            lambda b: b.select(cols), batch_format="pyarrow")

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def rename(b):
            return b.rename_columns(
                [mapping.get(c, c) for c in b.column_names])

        return self.map_batches(rename, batch_format="pyarrow")

    def limit(self, n: int) -> "Dataset":
        return self._with(L.Limit(n))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with(L.AllToAll("repartition",
                                     {"num_blocks": num_blocks}))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with(L.AllToAll("shuffle", {"seed": seed}))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with(L.AllToAll(
            "sort", {"key": key, "descending": descending}))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def union(self, *others: "Dataset") -> "Dataset":
        return self._with(L.Union(others=[o._ops for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._with(L.Zip(other=other._ops))

    # --------------------------------------------------------- consumption

    def iter_blocks(self) -> Iterator[Block]:
        for ref, _meta in self._stream():
            yield ray_tpu.get(ref)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy", drop_last: bool = False,
                     prefetch_batches: int = 1,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     streaming: bool = False):
        """Iterate fixed-size batches.

        ``streaming=True`` routes a read->map plan through the compiled
        streaming pipeline (`stream_batches`) instead of the task-based
        executor: shard readers -> transform actors -> batcher over
        slot-ring channels, ``prefetch_batches`` becoming the channel
        depth (the backpressure bound). Streaming yields numpy batches
        only and its windowed shuffle runs inside the batcher stage
        (same knobs, its own seeded stream) — plans that need barriers
        or materialized refs raise rather than silently falling back.
        """
        if streaming:
            if batch_format != "numpy":
                raise ValueError(
                    "streaming=True yields numpy batches only "
                    f"(got batch_format={batch_format!r})")
            return self.stream_batches(
                batch_size=batch_size,
                drop_last=drop_last,
                # the task path's default (1) means "default depth" here,
                # not a depth-1 ring; explicit zeros still raise inside
                prefetch_batches=prefetch_batches,
                shuffle_buffer=local_shuffle_buffer_size,
                seed=local_shuffle_seed)
        return self.iterator().iter_batches(
            batch_size=batch_size, batch_format=batch_format,
            drop_last=drop_last, prefetch_batches=prefetch_batches,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            local_shuffle_seed=local_shuffle_seed)

    def stream_batches(self, *, batch_size: Optional[int] = 256,
                       epochs: int = 1, seed: Optional[int] = 0,
                       shuffle_buffer: Optional[int] = None,
                       num_readers: Optional[int] = None,
                       prefetch_batches: Optional[int] = None,
                       depth: Optional[int] = None,
                       drop_last: bool = False, **kw):
        """Consume this dataset through the compiled streaming pipeline
        (`data/_internal/streaming.py`): shard readers -> transform
        actors -> a fixed-shape batcher over depth-k channels, zero
        steady-state control-plane RPCs per stage. Yields numpy-dict
        batches for ``epochs`` passes, the shard order re-seeded per
        epoch; the iterator's ``.epoch_stats`` carries per-epoch stall
        and RPC accounting and ``.executor`` exposes ``feed()`` for
        handing batches to a trainer without a copy.

        A plan ending in a seeded ``random_shuffle()`` or
        ``repartition()`` compiles onto the streaming ALL-TO-ALL
        exchange (`data/_internal/exchange.py`): R producers partition
        rows into per-consumer bucket frames over an R x C channel mesh
        instead of a task-executor barrier. An all-to-all plan the
        exchange can't run (unseeded shuffle, sort/groupby, chained
        barriers) RAISES with the reason — the barrier path stays
        available via iter_batches without streaming=True, never as a
        silent fallback."""
        from ray_tpu.data._internal import logical as _L
        from ray_tpu.data._internal.exchange import (
            ExchangeBatches, exchange_incompatible_reason)
        from ray_tpu.data._internal.streaming import StreamingBatches

        if depth is None and prefetch_batches is not None \
                and prefetch_batches != 1:
            # depth= is the precise knob (any ring depth, including 1);
            # prefetch_batches rides along from iter_batches, whose
            # task-path default of 1 means "default depth" here. An
            # explicit 0 on either raises inside (the falsy-zero lesson)
            depth = prefetch_batches
        if any(isinstance(op, _L.AllToAll) for op in self._ops):
            reason = exchange_incompatible_reason(self._ops)
            if reason is not None:
                raise ValueError(
                    f"streaming execution of this all-to-all plan is "
                    f"not supported: {reason}; run it on the "
                    f"task-based executor (iter_batches without "
                    f"streaming=True)")
            return ExchangeBatches(
                self._ops, batch_size=batch_size, epochs=epochs,
                seed=seed, shuffle_buffer=shuffle_buffer,
                num_producers=num_readers, depth=depth,
                drop_last=drop_last, **kw)
        return StreamingBatches(
            self._ops, batch_size=batch_size, epochs=epochs, seed=seed,
            shuffle_buffer=shuffle_buffer, num_readers=num_readers,
            depth=depth, drop_last=drop_last, **kw)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self.iter_blocks():
            yield from block_rows(block)

    def iterator(self) -> DataIterator:
        return _BlockStreamIterator(self)

    def iter_torch_batches(self, **kw):
        return self.iterator().iter_torch_batches(**kw)

    def iter_jax_batches(self, **kw):
        return self.iterator().iter_jax_batches(**kw)

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return list(self.iter_rows())

    def take_batch(self, batch_size: int = 20,
                   batch_format: str = "numpy") -> Any:
        for batch in self.limit(batch_size).iter_batches(
                batch_size=batch_size, batch_format=batch_format):
            return batch
        raise ValueError("dataset is empty")

    def count(self) -> int:
        return sum(resolve_meta(m)["num_rows"] for _, m in self._stream())

    def schema(self):
        for ref, _ in self._stream():
            return ray_tpu.get(ref).schema
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s is not None else []

    def size_bytes(self) -> int:
        return sum(resolve_meta(m)["size_bytes"] for _, m in self._stream())

    def num_blocks(self) -> int:
        return sum(1 for _ in self._stream())

    def stats(self) -> str:
        n, rows, size = 0, 0, 0
        for _, m in self._stream():
            m = resolve_meta(m)
            n += 1
            rows += m["num_rows"]
            size += m["size_bytes"]
        return (f"Dataset: {n} blocks, {rows} rows, {size} bytes; "
                f"plan={[type(o).__name__ for o in self._ops]}")

    # aggregates
    def sum(self, col: str):
        return self._agg(col, np.sum)

    def min(self, col: str):
        return self._agg(col, np.min)

    def max(self, col: str):
        return self._agg(col, np.max)

    def mean(self, col: str):
        total, count = 0.0, 0
        for b in self.iter_blocks():
            if b.num_rows:
                a = b.column(col).to_numpy(zero_copy_only=False)
                total += float(a.sum())
                count += len(a)
        return total / count if count else float("nan")

    def _agg(self, col: str, fn):
        vals = [fn(b.column(col).to_numpy(zero_copy_only=False))
                for b in self.iter_blocks() if b.num_rows]
        return fn(np.array(vals)).item() if vals else None

    # ------------------------------------------------------ materialization

    def materialize(self) -> "MaterializedDataset":
        refs, metas = [], []
        for ref, m in self._stream():
            refs.append(ref)
            metas.append(resolve_meta(m))
        return MaterializedDataset(
            [L.InputData(block_refs=refs, metas=metas)], self._concurrency)

    def split(self, n: int) -> List["MaterializedDataset"]:
        """Materialize and split into n contiguous sub-datasets
        (reference: Dataset.split)."""
        mat = self.materialize()
        src: L.InputData = mat._ops[0]
        cuts = even_cuts(len(src.block_refs), n)
        # pad so exactly n datasets come back (gang consumers index by rank)
        while len(cuts) - 1 < n:
            cuts.append(cuts[-1])
        return [
            MaterializedDataset(
                [L.InputData(block_refs=src.block_refs[cuts[i]:cuts[i + 1]],
                             metas=src.metas[cuts[i]:cuts[i + 1]])],
                self._concurrency)
            for i in builtins.range(n)
        ]

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None, epochs: int = 1,
                        seed: Optional[int] = 0) -> List[DataIterator]:
        """n iterators fed by one shared streaming execution
        (reference: Dataset.streaming_split / _StreamSplitDataIterator).

        A plan ending in a seeded ``random_shuffle()``/``repartition()``
        compiles onto the streaming all-to-all exchange: n consumer
        stages each own one output channel and every iterator reads its
        own rank's stream — deterministic partition-assigned splits
        (exact vs the task baseline at the same seed), with
        ``locality_hints`` (node_id_hex per rank) steering each
        consumer onto the node its reader lives on. Other plans are fed
        first-come-first-served by a coordinator actor (faster
        consumers do more work — the dynamic-balancing path); an
        all-to-all plan the exchange can't run (unseeded shuffle,
        sort/groupby) falls back to the coordinator WITH a logged
        reason, never silently."""
        from ray_tpu.data._internal.exchange import (
            ExchangeExecutor, exchange_incompatible_reason)
        from ray_tpu.data.iterator import (_ExchangeSplitIterator,
                                           _SplitCoordinator,
                                           _StreamSplitIterator)

        if any(isinstance(op, L.AllToAll) for op in self._ops):
            reason = exchange_incompatible_reason(self._ops)
            if reason is None:
                ex = ExchangeExecutor(
                    self._ops, batch_size=None, epochs=epochs, seed=seed,
                    num_consumers=n, locality_hints=locality_hints)
                return [_ExchangeSplitIterator(ex, rank=i)
                        for i in builtins.range(n)]
            logger.warning(
                "streaming_split falling back to the coordinator-fed "
                "task executor (all-to-all runs as a BARRIER): %s",
                reason)
        coord = ray_tpu.remote(_SplitCoordinator).options(
            num_cpus=0.1).remote(self._ops, self._concurrency, n, equal)
        return [_StreamSplitIterator(coord, rank=i) for i in builtins.range(n)]

    # ------------------------------------------------------------- writes

    def write_parquet(self, path: str) -> List[str]:
        return self._write(path, "parquet")

    def write_csv(self, path: str) -> List[str]:
        return self._write(path, "csv")

    def write_json(self, path: str) -> List[str]:
        return self._write(path, "json")

    def write_tfrecords(self, path: str) -> List[str]:
        return self._write(path, "tfrecords")

    def _write(self, path: str, fmt: str) -> List[str]:
        from ray_tpu.data.datasource import write_block

        w = ray_tpu.remote(write_block)
        refs = [w.remote(ref, path, i, fmt)
                for i, (ref, _m) in enumerate(self._stream())]
        return ray_tpu.get(refs)

    # ------------------------------------------------------------- interop

    def to_pandas(self, limit: Optional[int] = None):
        import pandas as pd

        ds = self.limit(limit) if limit else self
        frames = [b.to_pandas() for b in ds.iter_blocks()]
        if not frames:
            return pd.DataFrame()
        return pd.concat(frames, ignore_index=True)

    def to_arrow_refs(self) -> List[Any]:
        return [ref for ref, _ in self._stream()]

    def __repr__(self) -> str:
        return (f"Dataset(ops={[type(o).__name__ for o in self._ops]})")


class MaterializedDataset(Dataset):
    pass


# ------------------------------------------------------------- groupby


class GroupedData:
    """Analog of `ray.data.grouped_data.GroupedData`."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, agg_fn) -> Dataset:
        return self._ds._with(L.AllToAll(
            "groupby", {"key": self._key, "agg_fn": agg_fn}))

    def count(self) -> Dataset:
        key = self._key

        def fn(df):
            out = df.groupby(key, sort=True).size().reset_index(name="count()")
            return out

        return self._agg(fn)

    def sum(self, col: str) -> Dataset:
        return self._named_agg(col, "sum")

    def mean(self, col: str) -> Dataset:
        return self._named_agg(col, "mean")

    def min(self, col: str) -> Dataset:
        return self._named_agg(col, "min")

    def max(self, col: str) -> Dataset:
        return self._named_agg(col, "max")

    def std(self, col: str) -> Dataset:
        return self._named_agg(col, "std")

    def _named_agg(self, col: str, how: str) -> Dataset:
        key = self._key

        def fn(df):
            out = (df.groupby(key, sort=True)[col].agg(how)
                   .reset_index(name=f"{how}({col})"))
            return out

        return self._agg(fn)

    def map_groups(self, fn: Callable, *,
                   batch_format: str = "pandas") -> Dataset:
        key = self._key

        def apply(df):
            import pandas as pd

            outs = []
            for _, g in df.groupby(key, sort=True):
                if batch_format == "numpy":
                    res = fn({c: g[c].to_numpy() for c in g.columns})
                    outs.append(block_to_batch(batch_to_block(res), "pandas"))
                else:
                    outs.append(fn(g))
            return pd.concat(outs, ignore_index=True)

        return self._agg(apply)


# --------------------------------------------------------------- sources


def _input_dataset(blocks: List[Block], concurrency=DEFAULT_CONCURRENCY,
                   target_rows_per_block: Optional[int] = None) -> Dataset:
    refs, metas = [], []
    for b in blocks:
        refs.append(ray_tpu.put(b))
        metas.append(block_meta(b))
    return Dataset([L.InputData(block_refs=refs, metas=metas)], concurrency)


def _chunk(n_items: int, parallelism: int) -> List[Tuple[int, int]]:
    cuts = even_cuts(n_items, parallelism)
    return [(cuts[i], cuts[i + 1]) for i in builtins.range(len(cuts) - 1)
            if cuts[i] < cuts[i + 1]]


def from_items(items: List[Any], *, parallelism: int = 16) -> Dataset:
    if items and not isinstance(items[0], dict):
        items = [{"item": x} for x in items]
    blocks = [batch_to_block(items[lo:hi])
              for lo, hi in _chunk(len(items), parallelism)] or [
                  batch_to_block([])]
    return _input_dataset(blocks)


def range(n: int, *, parallelism: int = 16) -> Dataset:
    from ray_tpu.data.datasource import range_tasks

    return Dataset([L.Read(read_tasks=range_tasks(n, parallelism),
                           datasource_name="range")])


def range_tensor(n: int, *, shape: Tuple[int, ...] = (1,),
                 parallelism: int = 16) -> Dataset:
    from ray_tpu.data.datasource import range_tensor_tasks

    return Dataset([L.Read(read_tasks=range_tensor_tasks(n, shape,
                                                         parallelism),
                           datasource_name="range_tensor")])


def from_pandas(dfs) -> Dataset:
    import pandas as pd

    if isinstance(dfs, pd.DataFrame):
        dfs = [dfs]
    return _input_dataset([batch_to_block(df) for df in dfs])


def from_arrow(tables) -> Dataset:
    import pyarrow as pa

    if isinstance(tables, pa.Table):
        tables = [tables]
    return _input_dataset(list(tables))


def from_numpy(arrays, column: str = "data") -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = [arrays]
    return _input_dataset([batch_to_block({column: a}) for a in arrays])


def read_parquet(paths, *, columns=None, **_kw) -> Dataset:
    from ray_tpu.data.datasource import parquet_tasks

    return Dataset([L.Read(read_tasks=parquet_tasks(paths, columns),
                           datasource_name="parquet")])


def read_csv(paths, **_kw) -> Dataset:
    from ray_tpu.data.datasource import csv_tasks

    return Dataset([L.Read(read_tasks=csv_tasks(paths),
                           datasource_name="csv")])


def read_json(paths, **_kw) -> Dataset:
    from ray_tpu.data.datasource import json_tasks

    return Dataset([L.Read(read_tasks=json_tasks(paths),
                           datasource_name="json")])


def read_numpy(paths, **_kw) -> Dataset:
    from ray_tpu.data.datasource import numpy_tasks

    return Dataset([L.Read(read_tasks=numpy_tasks(paths),
                           datasource_name="numpy")])


def read_binary_files(paths, **_kw) -> Dataset:
    from ray_tpu.data.datasource import binary_tasks

    return Dataset([L.Read(read_tasks=binary_tasks(paths),
                           datasource_name="binary")])


def read_tfrecords(paths, **_kw) -> Dataset:
    """TFRecord files of tf.train.Example protos (pure-python parser —
    no tensorflow/protobuf dependency). ≈ `ray.data.read_tfrecords`."""
    from ray_tpu.data.datasource import tfrecord_tasks

    return Dataset([L.Read(read_tasks=tfrecord_tasks(paths),
                           datasource_name="tfrecords")])


def read_images(paths, *, size=None, mode=None, **_kw) -> Dataset:
    """Image files -> {"image": [H,W,C], "path"} rows (PIL decode).
    ≈ `ray.data.read_images`."""
    from ray_tpu.data.datasource import image_tasks

    return Dataset([L.Read(read_tasks=image_tasks(paths, size, mode),
                           datasource_name="images")])


def read_webdataset(paths, **_kw) -> Dataset:
    """WebDataset tar shards: one row per sample key, one column per member
    extension. ≈ `ray.data.read_webdataset`."""
    from ray_tpu.data.datasource import webdataset_tasks

    return Dataset([L.Read(read_tasks=webdataset_tasks(paths),
                           datasource_name="webdataset")])


def read_sql(sql: str, connection_factory, *, partition_column=None,
             lower_bound=None, upper_bound=None, parallelism: int = 1,
             **_kw) -> Dataset:
    """DBAPI-2 query -> dataset. ≈ `ray.data.read_sql`. With
    `partition_column` + bounds the read fans out into `parallelism`
    range-partitioned queries (warehouse parallel-read recipe)."""
    from ray_tpu.data.datasource import sql_tasks

    return Dataset([L.Read(
        read_tasks=sql_tasks(sql, connection_factory,
                             partition_column=partition_column,
                             lower_bound=lower_bound,
                             upper_bound=upper_bound,
                             parallelism=parallelism),
        datasource_name="sql")])


def read_orc(paths, **_kw) -> Dataset:
    """ORC files -> dataset (≈ `ray.data.read_orc`, pyarrow-native)."""
    from ray_tpu.data.datasource import orc_tasks

    return Dataset([L.Read(read_tasks=orc_tasks(paths),
                           datasource_name="orc")])


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline=None, parallelism: int = 4, client_factory=None,
               **_kw) -> Dataset:
    """MongoDB collection -> dataset (≈ `ray.data.read_mongo`): _id
    range partitions, one cursor task each."""
    from ray_tpu.data.datasource import mongo_tasks

    return Dataset([L.Read(
        read_tasks=mongo_tasks(uri, database, collection,
                               pipeline=pipeline, parallelism=parallelism,
                               client_factory=client_factory),
        datasource_name="mongo")])


def from_huggingface(hf_dataset, *, parallelism: int = 8) -> Dataset:
    """HuggingFace datasets.Dataset -> dataset (≈
    `ray.data.from_huggingface`): zero-copy via the underlying arrow
    table, split into `parallelism` blocks."""
    table = hf_dataset.data.table if hasattr(hf_dataset, "data") else None
    if table is None:
        raise TypeError("from_huggingface expects a datasets.Dataset "
                        "(arrow-backed)")
    n = max(1, table.num_rows)
    per = max(1, -(-n // parallelism))

    def make(lo, hi):
        return lambda: table.slice(lo, hi - lo)

    import builtins

    # this module shadows `range` with the ray.data.range constructor
    tasks = [make(lo, min(lo + per, n))
             for lo in builtins.range(0, n, per)]
    return Dataset([L.Read(read_tasks=tasks,
                           datasource_name="huggingface")])


def read_bigquery(project_id: str, *, dataset: str = None, query: str = None,
                  parallelism: int = 4, client_factory=None,
                  **_kw) -> Dataset:
    """Cloud-warehouse read (≈ `ray.data.read_bigquery`,
    `python/ray/data/datasource/bigquery_datasource.py`): a query's
    destination table (or a named table) read with one task per
    row-range stream. `client_factory` injects the client (production
    default: google.cloud.bigquery.Client, gated on the library)."""
    from ray_tpu.data.datasource import bigquery_tasks

    return Dataset([L.Read(
        read_tasks=bigquery_tasks(project_id, dataset=dataset, query=query,
                                  parallelism=parallelism,
                                  client_factory=client_factory),
        datasource_name="bigquery")])
