"""ray_tpu.data — lazy streaming distributed datasets (Ray Data analog,
`python/ray/data/`)."""

from ray_tpu.data.block import Block  # noqa: F401
from ray_tpu.data.dataset import (  # noqa: F401
    ActorPoolStrategy,
    Dataset,
    GroupedData,
    MaterializedDataset,
    from_arrow,
    from_huggingface,
    from_items,
    from_numpy,
    from_pandas,
    range,
    range_tensor,
    read_bigquery,
    read_binary_files,
    read_csv,
    read_images,
    read_json,
    read_mongo,
    read_numpy,
    read_orc,
    read_parquet,
    read_sql,
    read_tfrecords,
    read_webdataset,
)
from ray_tpu.data.iterator import DataIterator  # noqa: F401

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu("data")
del _rlu
