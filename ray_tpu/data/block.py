"""Blocks — the unit of distributed data.

Analog of the reference's `python/ray/data/block.py` +
`_internal/arrow_block.py`: a block is one pyarrow.Table living in the
object store; metadata (row count, byte size) travels as a second, inlined
task return so planners never fetch payloads to learn sizes. Batches
convert between arrow / pandas / numpy-dict at the boundary only.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, List, Optional

import numpy as np
import pyarrow as pa

Block = pa.Table


def even_cuts(n: int, parts: int) -> List[int]:
    """parts+1 cut points splitting n items as evenly as possible."""
    parts = max(1, min(parts, n or 1))
    return [round(i * n / parts) for i in range(parts + 1)]


def block_meta(block: Block) -> Dict[str, Any]:
    return {"num_rows": block.num_rows, "size_bytes": block.nbytes}


def batch_to_block(batch: Any) -> Block:
    """Accepts pyarrow.Table, pandas.DataFrame, dict of arrays/lists, or a
    list of row-dicts."""
    if isinstance(batch, pa.Table):
        return batch
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
    except ImportError:
        pass
    if isinstance(batch, dict):
        cols = {}
        for k, v in batch.items():
            v = np.asarray(v) if not isinstance(v, np.ndarray) else v
            if v.ndim > 1:
                # tensor column: nested arrow lists (ndarray → pylist, since
                # arrow can't infer nesting from an array of ndarrays)
                cols[k] = pa.array(v.tolist())
            else:
                cols[k] = pa.array(v)
        return pa.table(cols)
    if isinstance(batch, list):
        if batch and isinstance(batch[0], dict):
            keys = batch[0].keys()
            return batch_to_block({k: [r[k] for r in batch] for k in keys})
        return pa.table({"item": pa.array(batch)})
    raise TypeError(f"cannot convert {type(batch).__name__} to a block")


def _column_to_numpy(col: pa.ChunkedArray) -> np.ndarray:
    if pa.types.is_list(col.type) or pa.types.is_large_list(col.type):
        pylist = col.to_pylist()
        try:
            return np.asarray(pylist)
        except ValueError:  # ragged
            return np.asarray(pylist, dtype=object)
    try:
        return col.to_numpy(zero_copy_only=False)
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
        return np.asarray(col.to_pylist(), dtype=object)


def block_to_batch(block: Block, batch_format: str = "numpy") -> Any:
    if batch_format in ("pyarrow", "arrow"):
        return block
    if batch_format == "pandas":
        return block.to_pandas()
    if batch_format in ("numpy", "default"):
        return {name: _column_to_numpy(block.column(name))
                for name in block.column_names}
    raise ValueError(f"unknown batch_format {batch_format!r}")


def block_rows(block: Block) -> Iterator[Dict[str, Any]]:
    for row in block.to_pylist():
        yield row


def slice_block(block: Block, start: int, end: int) -> Block:
    return block.slice(start, end - start)


def concat_blocks(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if b is not None and b.num_rows > 0]
    if not blocks:
        return pa.table({})
    if len(blocks) == 1:
        return blocks[0]
    return pa.concat_tables(blocks, promote_options="permissive")


def batches_from_blocks(
    blocks: Iterable[Block],
    batch_size: Optional[int],
    batch_format: str = "numpy",
    drop_last: bool = False,
) -> Iterator[Any]:
    """Re-chunk a block stream into fixed-size batches (the reference's
    `_internal/block_batching/`)."""
    if batch_size is None:
        for b in blocks:
            if b.num_rows > 0:
                yield block_to_batch(b, batch_format)
        return
    carry: List[Block] = []
    carry_rows = 0
    for b in blocks:
        carry.append(b)
        carry_rows += b.num_rows
        while carry_rows >= batch_size:
            merged = concat_blocks(carry)
            yield block_to_batch(slice_block(merged, 0, batch_size),
                                 batch_format)
            merged = slice_block(merged, batch_size, merged.num_rows)
            carry = [merged]
            carry_rows = merged.num_rows
    if carry_rows > 0 and not drop_last:
        merged = concat_blocks(carry)
        if merged.num_rows:
            yield block_to_batch(merged, batch_format)
