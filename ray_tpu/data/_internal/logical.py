"""Logical plan operators + the fusion optimizer.

Analog of the reference's `python/ray/data/_internal/logical/` (operators,
rules, optimizers.py): a Dataset holds a linear chain of logical ops; before
execution, consecutive one-to-one ops (map/filter/flat_map/map_batches) are
fused into single block transforms (the reference's OperatorFusionRule) so
one task applies the whole chain to a block. All-to-all ops (repartition,
shuffle, sort, groupby) are pipeline barriers.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.data.block import (Block, batch_to_block, batches_from_blocks,
                                block_to_batch, concat_blocks)

# A BlockTransform maps one input block to one output block.
BlockTransform = Callable[[Block], Block]


class LogicalOp:
    name = "op"


@dataclasses.dataclass
class InputData(LogicalOp):
    """Pre-existing blocks (refs) — from_items/from_pandas/materialized."""

    block_refs: List[Any]
    metas: List[Dict[str, Any]]
    name = "InputData"


@dataclasses.dataclass
class Read(LogicalOp):
    """Lazy read: a list of zero-arg callables each producing one block."""

    read_tasks: List[Callable[[], Block]]
    datasource_name: str = "read"
    name = "Read"


@dataclasses.dataclass
class OneToOne(LogicalOp):
    """A fusible row/batch transform."""

    transform: BlockTransform
    label: str = "map"
    # per-op overrides; ops with any override don't fuse (their window /
    # resource request must be their own)
    concurrency: "int | None" = None
    num_cpus: "float | None" = None
    name = "OneToOne"


@dataclasses.dataclass
class ActorPoolMap(LogicalOp):
    """A stateful batch transform on a pool of long-lived actors
    (≈ actor_pool_map_operator.py): the UDF is a class constructed once
    per actor; blocks stream through the pool. Never fused."""

    fn_cls: Any
    fn_constructor_args: tuple = ()
    fn_constructor_kwargs: dict = dataclasses.field(default_factory=dict)
    batch_size: "int | None" = None
    batch_format: str = "numpy"
    fn_args: tuple = ()
    fn_kwargs: dict = dataclasses.field(default_factory=dict)
    pool_size: int = 2
    max_tasks_in_flight_per_actor: int = 2
    num_cpus: float = 1.0
    label: str = "actor_map"
    name = "ActorPoolMap"


@dataclasses.dataclass
class Limit(LogicalOp):
    n: int = 0
    name = "Limit"


@dataclasses.dataclass
class AllToAll(LogicalOp):
    """Barrier op; `kind` in {repartition, shuffle, sort, groupby}."""

    kind: str
    args: Dict[str, Any] = dataclasses.field(default_factory=dict)
    name = "AllToAll"


@dataclasses.dataclass
class Union(LogicalOp):
    others: List[List[LogicalOp]] = dataclasses.field(default_factory=list)
    name = "Union"


@dataclasses.dataclass
class Zip(LogicalOp):
    other: List[LogicalOp] = dataclasses.field(default_factory=list)
    name = "Zip"


# ------------------------------------------------------------- transforms


def make_map_batches_transform(
    fn: Callable,
    batch_size: Optional[int],
    batch_format: str,
    fn_args: Tuple = (),
    fn_kwargs: Optional[Dict] = None,
) -> BlockTransform:
    fn_kwargs = fn_kwargs or {}

    def transform(block: Block) -> Block:
        outs = []
        for batch in batches_from_blocks([block], batch_size, batch_format):
            out = fn(batch, *fn_args, **fn_kwargs)
            outs.append(batch_to_block(out))
        return concat_blocks(outs)

    return transform


def make_map_rows_transform(fn: Callable) -> BlockTransform:
    def transform(block: Block) -> Block:
        rows = [fn(row) for row in block.to_pylist()]
        # empty input: keep an empty block rather than letting the list
        # fallback invent an 'item' schema
        return batch_to_block(rows) if rows else block.slice(0, 0)

    return transform


def make_flat_map_transform(fn: Callable) -> BlockTransform:
    def transform(block: Block) -> Block:
        rows = []
        for row in block.to_pylist():
            rows.extend(fn(row))
        return batch_to_block(rows) if rows else block.slice(0, 0)

    return transform


def make_filter_transform(fn: Callable) -> BlockTransform:
    def transform(block: Block) -> Block:
        import numpy as np
        import pyarrow as pa

        mask = np.fromiter((bool(fn(r)) for r in block.to_pylist()),
                           dtype=bool, count=block.num_rows)
        return block.filter(pa.array(mask))

    return transform


def make_add_column_transform(name: str, fn: Callable) -> BlockTransform:
    def transform(block: Block) -> Block:
        batch = block_to_batch(block, "pandas")
        batch[name] = fn(batch)
        return batch_to_block(batch)

    return transform


def fuse_transforms(ts: List[BlockTransform]) -> BlockTransform:
    if len(ts) == 1:
        return ts[0]

    def fused(block: Block) -> Block:
        for t in ts:
            block = t(block)
        return block

    return fused
