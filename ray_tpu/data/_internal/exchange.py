"""Streaming all-to-all exchange: shuffle/repartition as channel stages.

`Dataset.random_shuffle` / `repartition` lower to barrier `AllToAll`
ops in the task executor: every input block materializes in the object
store, a split task fans each block into n parts, and a concat task per
output partition gathers them — the whole epoch's data sits still while
the barrier turns over. This module rebuilds those ops the way
`streaming.py` rebuilt read->map ingest: a fixed R x C mesh of
long-lived actors connected by depth-k slot-ring channels
(`_private/channels.py`), planned once at build time, streaming bucket
frames thereafter with ZERO steady-state control-plane RPCs per
producer and per consumer (counter-proven via the
``ray_tpu_rpc_client_calls_total`` deltas every epoch report carries).

Topology::

    R producers ----(R x C bucket-frame channels)----> C consumers
      (shard read      every producer holds ONE open      (merge ->
       + fused map      channel PER CONSUMER, placed       shuffle ->
       + partition)     on the CONSUMER's node)            batch)
                                                             |
                                              C consumer->driver channels
                                              (merged round-robin, or one
                                               per streaming_split rank /
                                               PipelineTrainer dp rank)

* every channel lives on its READER's node: same-node edges are
  zero-copy arena seqlock ops, cross-node edges are chunked mirror
  pushes (the collective ring's chunked framing applied to data);
* channel depth = the backpressure bound: a producer can run at most
  ``depth`` bucket frames ahead of each consumer
  (``RAY_TPU_DATA_EXCHANGE_DEPTH``);
* a block's per-consumer bucket larger than
  ``RAY_TPU_DATA_EXCHANGE_BUCKET_ROWS`` streams as several frames, so
  one fat block never needs a channel slot sized to hold it whole;
* an EMPTY bucket still sends one (zero-row) frame — the merge order
  stays deterministic and a consumer can prove it missed nothing.

Determinism (the parity contract): the epoch's shard order is
``epoch_order(T, seed, epoch)`` — producer r executes global positions
``p % R == r`` in order. For position p the row->consumer assignment is
``exchange_assignments(kind, C, rows, part_seed, epoch, p)`` — the
epoch FOLDED INTO the partition hash, so shuffles re-deal every epoch
with zero control messages. Consumer c reads its R input channels in
global-position order (position p's bucket comes from producer p % R),
which reconstructs the global bucket order EXACTLY, then runs the SAME
seeded-shuffle/batch stream (`epoch_batch_stream`) the task-based
baseline runs. ``task_exchange_batches`` IS that baseline: the same
partition function run as a real two-phase task shuffle through the
object store (one split task per block, ``num_returns=C``) — the
``algo="kv"`` idiom: a measured comparison target, never a silent
fallback. Same seed => same batches, exactly, on every consumer rank
and on the merged driver stream.

Failure semantics follow the house pattern: the participants set spans
the driver, every producer, every consumer and their nodes, so ANY
participant's death closes EVERY channel of the mesh; blocked peers
raise ``ChannelClosedError`` instead of hanging, stage loops re-fan the
close, pins return to baseline, and a partially-consumed epoch surfaces
a clean error — never a silently truncated shuffle.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

import ray_tpu
from ray_tpu._private import channels as _channels
from ray_tpu._private import chaos, flight, serialization
from ray_tpu._private.exceptions import ChannelClosedError
from ray_tpu._private.metrics import Counter, Gauge
from ray_tpu.data._internal.streaming import (_copy_batch, _np_concat,
                                              _np_rows, _np_slice, _np_take,
                                              _require_positive, epoch_order,
                                              epoch_batch_stream,
                                              shuffle_rng,
                                              split_streamable_plan)

logger = logging.getLogger(__name__)

# exchange kinds a streaming plan can compile onto (the other AllToAll
# kinds — sort, groupby — stay task-executor barriers)
EXCHANGE_KINDS = ("shuffle", "repartition")

# flight-recorder span ids for the mesh hot loops (per-thread ring
# records — no locks, no RPCs, so the zero-RPC proofs hold recorder-on)
_F_SEND = flight.intern("data.exchange_send")
_F_MERGE = flight.intern("data.exchange_merge")
_F_STALL = flight.intern("data.exchange_stall")

_m_ex_rows = Counter(
    "ray_tpu_data_exchange_rows_total",
    "Streaming exchange: rows streamed per producer->consumer edge "
    "(label edge=\"r->c\")")
_m_ex_bytes = Counter(
    "ray_tpu_data_exchange_bytes_total",
    "Streaming exchange: packed bucket-frame bytes per edge")
_m_ex_buckets = Counter(
    "ray_tpu_data_exchange_buckets_total",
    "Streaming exchange: bucket frames committed per edge (>= one per "
    "(block, consumer) pair — empty buckets still send one frame)")
_m_ex_skew = Gauge(
    "ray_tpu_data_exchange_consumer_skew",
    "max/mean rows per consumer of the most recently completed exchange "
    "epoch (1.0 = perfectly balanced; driver-observed)")


# ------------------------------------------------------------------- knobs


def _env_exchange_depth(config) -> int:
    """Exchange channel depth from config, rejecting an explicit env
    zero loudly (the PR-8/9 falsy-zero lesson: 0 never silently means
    a default — unset the var for that)."""
    raw = os.environ.get("RAY_TPU_DATA_EXCHANGE_DEPTH")
    if raw is not None and int(raw) <= 0:
        raise ValueError(
            f"RAY_TPU_DATA_EXCHANGE_DEPTH={raw!r}: explicit zeros are "
            f"rejected (unset the var for the default)")
    return _require_positive("data_exchange_depth",
                             config.data_exchange_depth)


def _env_bucket_rows(config) -> int:
    """Max rows per bucket frame, rejecting an explicit env zero."""
    raw = os.environ.get("RAY_TPU_DATA_EXCHANGE_BUCKET_ROWS")
    if raw is not None and int(raw) <= 0:
        raise ValueError(
            f"RAY_TPU_DATA_EXCHANGE_BUCKET_ROWS={raw!r}: explicit zeros "
            f"are rejected (unset the var for the default)")
    return _require_positive("data_exchange_bucket_rows",
                             config.data_exchange_bucket_rows)


# ------------------------------------------------- deterministic semantics


def partition_rng(seed: int, epoch: int, pos: int) -> np.random.Generator:
    """The row->consumer assignment RNG of one (epoch, global block
    position): epoch and position are FOLDED INTO the key, so every
    participant derives the same deal locally and epochs re-shuffle for
    free. Shared by the producer stage and the task-based baseline."""
    if seed is None:
        raise ValueError("exchange shuffle partitioning needs an "
                         "explicit seed")
    return np.random.default_rng(
        [int(seed) & 0x7FFFFFFF, 0xA77A, int(epoch), int(pos)])


def exchange_assignments(kind: str, num_consumers: int, num_rows: int,
                         seed: Optional[int], epoch: int,
                         pos: int) -> np.ndarray:
    """Row -> consumer assignment of one block: THE partition function,
    run by streaming producers on numpy rows and by the task baseline's
    split tasks on arrow rows — one implementation, parity by
    construction.

    shuffle: seeded uniform deal, re-keyed per (seed, epoch, position).
    repartition: position-offset round-robin deal — balanced to +-1 row
    per consumer per block and locally derivable (no global row offsets,
    which a streaming producer cannot know)."""
    C = int(num_consumers)
    if C <= 1:
        return np.zeros(num_rows, dtype=np.int64)
    if kind == "shuffle":
        return partition_rng(seed, epoch, pos).integers(
            0, C, size=num_rows)
    if kind == "repartition":
        return (np.arange(num_rows, dtype=np.int64) + pos) % C
    raise ValueError(f"unknown exchange kind {kind!r}")


def consumer_shuffle_params(kind: str, shuffle_buffer: Optional[int],
                            batch_size: Optional[int],
                            partition_seed: Optional[int],
                            order_seed: Optional[int]):
    """(buffer_rows, rng_seed) of the consumer-side windowed shuffle —
    shared by the consumer stage and the task baseline.

    kind == "shuffle": the exchange IS the shuffle, but rows inside one
    consumer would otherwise keep deterministic block order, so each
    consumer re-shuffles its own stream through the windowed buffer
    (default: 4 batches) seeded from the shuffle op's seed (per-rank rng
    stream). kind == "repartition": no implicit shuffle — an explicit
    ``shuffle_buffer`` rides the stream seed, exactly like
    ``Dataset.stream_batches``."""
    if kind == "shuffle":
        if batch_size is None:
            # split/block mode: buckets pass through un-batched; only an
            # explicit buffer re-shuffles within the rank stream
            if shuffle_buffer:
                return int(shuffle_buffer), partition_seed
            return None, None
        return int(shuffle_buffer or 4 * batch_size), partition_seed
    if shuffle_buffer:
        return int(shuffle_buffer), order_seed
    return None, None


def exchange_incompatible_reason(ops) -> Optional[str]:
    """None when the plan compiles onto the streaming exchange, else a
    human-readable reason — the string every fallback seam must SURFACE
    (log or raise), never swallow."""
    from ray_tpu.data._internal import logical as L

    if not ops:
        return "empty plan"
    a2a = [op for op in ops if isinstance(op, L.AllToAll)]
    if not a2a:
        return "no shuffle/repartition op to exchange"
    if not isinstance(ops[-1], L.AllToAll):
        return (f"ops after the {a2a[-1].kind} barrier "
                f"({type(ops[-1]).__name__}) — the exchange must be the "
                f"terminal stage")
    if len(a2a) > 1:
        return "more than one all-to-all op (chained barriers)"
    op = ops[-1]
    if op.kind not in EXCHANGE_KINDS:
        return (f"AllToAll kind {op.kind!r} is a true barrier (only "
                f"{'/'.join(EXCHANGE_KINDS)} stream)")
    if op.kind == "shuffle" and op.args.get("seed") is None:
        return ("unseeded random_shuffle() — the streaming exchange "
                "derives every epoch's deal from the seed; pass "
                "random_shuffle(seed=...)")
    try:
        split_streamable_plan(ops[:-1])
    except ValueError as e:
        return str(e)
    return None


def split_exchange_plan(ops):
    """(read_tasks, fused_transform_or_None, kind, kind_args) of an
    exchange-compatible plan: Read -> OneToOne* -> AllToAll(shuffle |
    repartition). Raises with the incompatibility reason otherwise —
    never a silent fallback."""
    reason = exchange_incompatible_reason(ops)
    if reason is not None:
        raise ValueError(
            f"plan does not compile onto the streaming exchange: "
            f"{reason}; run it on the task-based executor "
            f"(iter_batches without streaming=True)")
    tasks, fused = split_streamable_plan(ops[:-1])
    op = ops[-1]
    return tasks, fused, op.kind, dict(op.args)


# --------------------------------------------------- task-based baseline


def _split_exchange(block, kind, n, seed, epoch, pos) -> List[Any]:
    """Phase-1 split task of the barrier baseline: one block -> n bucket
    blocks via the SAME assignment function the streaming producers run."""
    import pyarrow as pa

    assign = exchange_assignments(kind, n, block.num_rows, seed, epoch, pos)
    return [block.filter(pa.array(assign == c)) for c in range(n)]


_split_exchange_r = ray_tpu.remote(_split_exchange)


def _round_robin(iters: List[Iterator]) -> Iterator:
    """Deterministic interleave: one item per live stream per sweep,
    dropping a stream at the point it exhausts — the exact merge order
    the driver's merged ``batches()`` runs over consumer channels."""
    live = list(iters)
    while live:
        for it in list(live):
            try:
                yield next(it)
            except StopIteration:
                live.remove(it)


def task_exchange_batches(ops, *, batch_size: Optional[int],
                          num_consumers: int,
                          consumer_rank: Optional[int] = None,
                          epoch: int = 1, seed: Optional[int] = 0,
                          shuffle_buffer: Optional[int] = None,
                          drop_last: bool = False,
                          concurrency: int = 8
                          ) -> Iterator[Dict[str, np.ndarray]]:
    """One epoch through the TASK-BASED barrier AllToAll at the
    exchange's exact semantics: the epoch's shard order re-applied to
    the read tasks, real remote read+transform tasks through the object
    store, a BARRIER (every block materialized), one split task per
    block (``num_returns=C``), then per-consumer bucket gathers in
    global order through the SAME shuffle+batch stream. This is the
    measured baseline of the ``data_shuffle_streaming_vs_barrier``
    probe and the parity reference of the exchange tests/chaos soak —
    same seed => same batches, exactly.

    ``consumer_rank=None`` yields the driver-merged round-robin stream
    (what ``ExchangeExecutor.batches()`` produces); a rank yields that
    consumer's own stream (what ``streaming_split``/``feed(rank=)``
    consume)."""
    from ray_tpu.data._internal import logical as L
    from ray_tpu.data._internal.executor import execute_plan
    from ray_tpu.data.block import block_to_batch

    tasks, fused, kind, args = split_exchange_plan(ops)
    C = _require_positive("num_consumers", num_consumers)
    part_seed = args.get("seed") if kind == "shuffle" else None
    order = epoch_order(len(tasks), seed, epoch)
    plan: List[Any] = [L.Read(read_tasks=[tasks[int(i)] for i in order],
                              datasource_name="exchange_epoch")]
    if fused is not None:
        plan.append(L.OneToOne(fused, label="exchange_map"))
    # the barrier: every block materializes before any bucket is read
    pairs = list(execute_plan(plan, concurrency))
    parts: List[List[Any]] = []
    for p, (ref, _meta) in enumerate(pairs):
        if C == 1:
            parts.append([ref])
        else:
            r = _split_exchange_r.options(num_returns=C).remote(
                ref, kind, C, part_seed, epoch, p)
            parts.append(list(r))

    def consumer_stream(c: int) -> Iterator[Dict[str, np.ndarray]]:
        def np_buckets():
            for p in range(len(pairs)):
                nb = block_to_batch(ray_tpu.get(parts[p][c]), "numpy")
                if _np_rows(nb):
                    yield nb

        buf, sseed = consumer_shuffle_params(
            kind, shuffle_buffer, batch_size, part_seed, seed)
        rng = shuffle_rng(sseed, epoch, rank=c) if buf else None
        if batch_size is None:
            blocks = np_buckets()
            if buf:
                from ray_tpu.data._internal.streaming import \
                    _shuffle_np_stream

                blocks = _shuffle_np_stream(blocks, buf, rng)
            return blocks
        return epoch_batch_stream(
            np_buckets(), batch_size=batch_size, shuffle_buffer=buf,
            rng=rng, drop_last=drop_last)

    if consumer_rank is not None:
        yield from consumer_stream(int(consumer_rank))
        return
    yield from _round_robin([consumer_stream(c) for c in range(C)])


# ------------------------------------------------------------------ plans


@dataclasses.dataclass
class _ProducerPlan:
    out_specs: List[_channels.ChannelSpec]  # one per consumer, c-indexed
    rank: int
    num_producers: int
    num_consumers: int
    num_tasks: int
    order_seed: Optional[int]
    kind: str
    partition_seed: Optional[int]
    epochs: int
    bucket_rows: int


@dataclasses.dataclass
class _ConsumerPlan:
    in_specs: List[_channels.ChannelSpec]  # one per producer, r-indexed
    out_spec: _channels.ChannelSpec
    rank: int
    num_producers: int
    num_consumers: int
    num_tasks: int
    order_seed: Optional[int]
    kind: str
    partition_seed: Optional[int]
    epochs: int
    batch_size: Optional[int]  # None: split mode — buckets pass through
    shuffle_buffer: Optional[int]
    drop_last: bool


# ------------------------------------------------------- stage actor loops


class _ExchangeProducerImpl:
    """Producer actor: executes its share of the epoch's read order
    (``p % R == rank``), applies the fused map chain, partitions each
    block's rows into per-consumer buckets with the shared assignment
    function, and streams bucket frames into its C open channels — the
    object store never sees a row."""

    def __init__(self, tasks, transform):
        self._tasks = list(tasks)
        self._transform = transform

    def ping(self) -> str:
        return "ok"

    def probe_sizes(self, sample: int = 3) -> dict:
        """Packed payload sizes off a few sample tasks so the driver can
        size the mesh's channels at build — an undersized buffer then
        can only be a loud build/write error, never silent corruption."""
        from ray_tpu.data.block import block_to_batch

        T = len(self._tasks)
        idx = sorted({0, T // 2, T - 1})[:max(1, int(sample))]
        np_b = row_b = 1
        for i in idx:
            block = self._tasks[i]()
            out = (self._transform(block) if self._transform is not None
                   else block)
            nb = block_to_batch(out, "numpy")
            payload = len(serialization.pack(
                {"p": 0, "last": True, "b": nb}))
            np_b = max(np_b, payload)
            row_b = max(row_b, payload // max(1, out.num_rows))
        return {"np_bytes": np_b, "row_bytes": row_b}

    def run_loop(self, plan: _ProducerPlan) -> dict:
        from ray_tpu._private import api, rpc
        from ray_tpu.data.block import block_to_batch

        core = api._core
        if core is None:
            raise RuntimeError("exchange producer loop outside a worker")
        open_local, local, release_pins = _channels.open_local_factory(core)
        remote_specs: List[_channels.ChannelSpec] = []
        outs: List[_channels.VersionedWriter] = []
        try:
            for spec in plan.out_specs:
                w = _channels.VersionedWriter(core, spec, open_local)
                if not w.is_local:
                    remote_specs.append(spec)
                outs.append(w)
        except BaseException:
            release_pins()
            raise

        def close_everything() -> None:
            _channels.close_channels_nowait(
                core, local.values(), remote_specs)

        R, C = plan.num_producers, plan.num_consumers
        sent = [0] * C  # per-edge messages committed (version 2n)
        edge = [f"{plan.rank}->{c}" for c in range(C)]
        total_rows = 0
        prev_rpc = rpc._m_client_calls.total()

        def send(c: int, payload) -> None:
            sent[c] += 1
            outs[c].write(payload, 2 * sent[c])

        try:
            for epoch in range(1, plan.epochs + 1):
                order = epoch_order(plan.num_tasks, plan.order_seed, epoch)
                blocks = 0
                rows = 0
                for p in range(plan.rank, plan.num_tasks, R):
                    chaos.maybe_crash("worker.data_exchange")
                    block = self._tasks[int(order[p])]()
                    out = (self._transform(block)
                           if self._transform is not None else block)
                    nb = block_to_batch(out, "numpy")
                    del block, out
                    n = _np_rows(nb)
                    assign = exchange_assignments(
                        plan.kind, C, n, plan.partition_seed, epoch, p)
                    for c in range(C):
                        t0 = flight.now()
                        idx = np.flatnonzero(assign == c)
                        bucket = _np_take(nb, idx)
                        bn = len(idx)
                        # >= one frame per (block, consumer) — an empty
                        # bucket still sends its zero-row frame so the
                        # consumer's deterministic merge can't stall on
                        # a bucket that will never come
                        lo = 0
                        while True:
                            hi = min(lo + plan.bucket_rows, bn)
                            payload = serialization.pack(
                                {"p": p, "last": hi >= bn,
                                 "b": _np_slice(bucket, lo, hi)})
                            send(c, payload)
                            _m_ex_buckets.inc(labels={"edge": edge[c]})
                            _m_ex_bytes.inc(len(payload),
                                            labels={"edge": edge[c]})
                            lo = hi
                            if lo >= bn:
                                break
                        _m_ex_rows.inc(bn, labels={"edge": edge[c]})
                        flight.span_since(_F_SEND, t0)
                    rows += n
                    blocks += 1
                total_rows += rows
                now = rpc._m_client_calls.total()
                stats = {"role": "producer", "rank": plan.rank,
                         "epoch": epoch, "blocks": blocks, "rows": rows,
                         "rpc_calls": now - prev_rpc}
                prev_rpc = now
                for c in range(C):
                    # producer stats ride consumer 0's eof only, so the
                    # driver sees each producer's report exactly once
                    send(c, serialization.pack(
                        {"eof": epoch,
                         "stats": [stats] if c == 0 else []}))
            return {"rows": total_rows, "epochs": plan.epochs}
        except ChannelClosedError:
            # teardown (or a peer's death) closed the mesh mid-epoch;
            # re-fan the close so every peer unwinds
            try:
                close_everything()
            except Exception:
                logger.exception("producer close-on-exit failed")
            return {"rows": total_rows, "closed": True}
        except BaseException:
            try:
                close_everything()
            except Exception:
                logger.exception("producer close-on-error failed")
            raise
        finally:
            release_pins()


class _ExchangeConsumerImpl:
    """Consumer actor: reads its R input channels in global-position
    order (position p's frames come from producer ``p % R`` — the
    deterministic merge), re-assembles multi-frame buckets, runs the
    shared windowed-shuffle + fixed-shape batch stream, and commits one
    batch per write into its driver-side output channel."""

    def ping(self) -> str:
        return "ok"

    def run_loop(self, plan: _ConsumerPlan) -> dict:
        from ray_tpu._private import api, rpc

        core = api._core
        if core is None:
            raise RuntimeError("exchange consumer loop outside a worker")
        open_local, local, release_pins = _channels.open_local_factory(core)
        remote_specs: List[_channels.ChannelSpec] = []
        try:
            in_chs = [open_local(s) for s in plan.in_specs]
            out = _channels.VersionedWriter(core, plan.out_spec, open_local)
            if not out.is_local:
                remote_specs.append(plan.out_spec)
        except BaseException:
            release_pins()
            raise

        def close_everything() -> None:
            _channels.close_channels_nowait(
                core, local.values(), remote_specs)

        R = plan.num_producers
        reads = [0] * R  # per-upstream message count
        m = 0  # downstream messages committed
        total_batches = 0
        prev_rpc = rpc._m_client_calls.total()
        try:
            for epoch in range(1, plan.epochs + 1):
                stage_stats: List[dict] = []
                rows_in = 0

                def np_buckets():
                    nonlocal rows_in
                    for p in range(plan.num_tasks):
                        chaos.maybe_crash("worker.data_exchange")
                        r = p % R
                        frames: List[Dict[str, np.ndarray]] = []
                        while True:
                            reads[r] += 1
                            view = in_chs[r].read(2 * reads[r])
                            msg = serialization.unpack(view)
                            if msg["p"] != p:
                                raise RuntimeError(
                                    f"exchange merge desync: consumer "
                                    f"{plan.rank} expected position {p} "
                                    f"from producer {r}, got {msg['p']}")
                            b = _copy_batch(msg["b"])  # memcpy, then ack
                            last = msg["last"]
                            del msg, view
                            in_chs[r].ack(0, 2 * reads[r])
                            if _np_rows(b):
                                frames.append(b)
                            if last:
                                break
                        if frames:
                            t0 = flight.now()
                            merged = _np_concat(frames)
                            flight.span_since(_F_MERGE, t0)
                            rows_in += _np_rows(merged)
                            # one block per (position, consumer) bucket,
                            # frames re-joined — the SAME block stream
                            # the baseline's split tasks produce, so the
                            # windowed shuffle fills at identical points
                            yield merged
                    for r in range(R):
                        reads[r] += 1
                        view = in_chs[r].read(2 * reads[r])
                        msg = serialization.unpack(bytes(view))
                        del view
                        in_chs[r].ack(0, 2 * reads[r])
                        if msg.get("eof") != epoch:
                            raise RuntimeError(
                                f"exchange epoch desync: consumer "
                                f"{plan.rank} expected eof {epoch} from "
                                f"producer {r}, got {msg!r}")
                        stage_stats.extend(msg.get("stats", []))

                buf, sseed = consumer_shuffle_params(
                    plan.kind, plan.shuffle_buffer, plan.batch_size,
                    plan.partition_seed, plan.order_seed)
                rng = (shuffle_rng(sseed, epoch, rank=plan.rank)
                       if buf else None)
                if plan.batch_size is None:
                    stream: Iterator = np_buckets()
                    if buf:
                        from ray_tpu.data._internal.streaming import \
                            _shuffle_np_stream

                        stream = _shuffle_np_stream(stream, buf, rng)
                else:
                    stream = epoch_batch_stream(
                        np_buckets(), batch_size=plan.batch_size,
                        shuffle_buffer=buf, rng=rng,
                        drop_last=plan.drop_last)
                batches = 0
                for batch in stream:
                    m += 1
                    out.write(serialization.pack({"b": batch}), 2 * m)
                    batches += 1
                total_batches += batches
                now = rpc._m_client_calls.total()
                stage_stats.append({"role": "consumer", "rank": plan.rank,
                                    "epoch": epoch, "rows": rows_in,
                                    "batches": batches,
                                    "rpc_calls": now - prev_rpc})
                prev_rpc = now
                m += 1
                out.write(serialization.pack(
                    {"eof": epoch, "batches": batches, "rows": rows_in,
                     "stats": stage_stats}), 2 * m)
            return {"batches": total_batches, "epochs": plan.epochs}
        except ChannelClosedError:
            try:
                close_everything()
            except Exception:
                logger.exception("consumer close-on-exit failed")
            return {"batches": total_batches, "closed": True}
        except BaseException:
            try:
                close_everything()
            except Exception:
                logger.exception("consumer close-on-error failed")
            raise
        finally:
            release_pins()


_producer_cls = _consumer_cls = None


def _actor_classes():
    global _producer_cls, _consumer_cls
    if _producer_cls is None:
        _producer_cls = ray_tpu.remote(_ExchangeProducerImpl)
        _consumer_cls = ray_tpu.remote(_ExchangeConsumerImpl)
    return _producer_cls, _consumer_cls


# --------------------------------------------------------------- executor


class ExchangeExecutor:
    """Compiled R x C streaming exchange (module docstring has the
    design)::

        ex = ExchangeExecutor(ds._ops, batch_size=256, epochs=2, seed=0,
                              num_consumers=2)
        for batch in ex.batches():        # merged round-robin stream
            ...
        ex.shutdown()

    Per-rank consumption (streaming_split ranks, PipelineTrainer dp
    ranks) reads ONE consumer's output channel::

        for batch in ex.rank_batches(rank):  ...
        for out in ex.feed(step, rank=r):    ...  # read-only arena views
    """

    def __init__(self, ops, *, batch_size: Optional[int], epochs: int = 1,
                 seed: Optional[int] = 0,
                 num_producers: Optional[int] = None,
                 num_consumers: Optional[int] = None,
                 shuffle_buffer: Optional[int] = None,
                 depth: Optional[int] = None,
                 bucket_rows: Optional[int] = None,
                 drop_last: bool = False,
                 buffer_bytes: Optional[int] = None,
                 batch_buffer_bytes: Optional[int] = None,
                 producer_options: Optional[Sequence[dict]] = None,
                 consumer_options: Optional[Sequence[dict]] = None,
                 locality_hints: Optional[Sequence] = None,
                 name: str = "data_exchange"):
        from ray_tpu._private import api

        core = api._require_core()
        self._core = core
        if core.arena is None:
            raise RuntimeError(
                "the streaming exchange needs a driver attached to a "
                "node arena")
        if batch_size is not None:
            batch_size = _require_positive("batch_size", batch_size)
        self._batch_size = batch_size
        self._epochs = _require_positive("epochs", epochs)
        self._seed = seed
        if shuffle_buffer is not None and int(shuffle_buffer) <= 0:
            raise ValueError(
                f"shuffle_buffer must be positive (got {shuffle_buffer!r});"
                f" pass None for the kind's default")
        self._shuffle = int(shuffle_buffer) if shuffle_buffer else None
        self._depth = (_require_positive("depth", depth)
                       if depth is not None
                       else _env_exchange_depth(core.config))
        self._bucket_rows = (_require_positive("bucket_rows", bucket_rows)
                             if bucket_rows is not None
                             else _env_bucket_rows(core.config))
        self._drop_last = bool(drop_last)
        self._tasks, self._transform, self._kind, self._kind_args = \
            split_exchange_plan(ops)
        self._part_seed = (self._kind_args.get("seed")
                           if self._kind == "shuffle" else None)
        T = len(self._tasks)
        self._T = T
        R = (min(4, T) if num_producers is None
             else _require_positive("num_producers", num_producers))
        self._R = R = min(R, T)
        if num_consumers is None:
            num_consumers = self._kind_args.get("num_blocks") \
                if self._kind == "repartition" else None
        C = (2 if num_consumers is None
             else _require_positive("num_consumers", num_consumers))
        if self._kind == "repartition":
            nb = self._kind_args.get("num_blocks")
            if nb and num_consumers is not None and int(nb) != C:
                raise ValueError(
                    f"repartition(num_blocks={nb}) conflicts with "
                    f"num_consumers={C}; drop one of them")
        self._C = C
        if locality_hints is not None and len(locality_hints) != C:
            raise ValueError(
                f"locality_hints must name one node per consumer "
                f"({C}), got {len(locality_hints)}")

        self._dead = False
        self._torn = False
        self._teardown_lock = threading.Lock()
        self._all_specs: List[_channels.ChannelSpec] = []
        self._local_channels: Dict[bytes, _channels.LocalChannel] = {}
        self._loop_refs: List[Any] = []
        self._actor_info: Dict[str, dict] = {}
        self._producers: List[Any] = []
        self._consumers: List[Any] = []
        self._m = [0] * C  # per-consumer messages the driver has read
        self._epoch_stats: List[dict] = []
        self._rank_epoch_stats: List[List[dict]] = [[] for _ in range(C)]
        self._rank_epoch_done = [0] * C
        self._mode: Optional[str] = None  # "merged" | "ranks"
        self._consuming = [False] * C
        self._exhausted = False

        producer_cls, consumer_cls = _actor_classes()

        # deterministic mesh placement: producers and consumers round-
        # robin across live nodes (soft affinity — a full node falls
        # back to the scheduler and resolve_actor_placement records the
        # miss); explicit options/locality_hints override per actor
        plan_nodes = None
        try:
            views = core._run(core.clients.get(
                core.controller_addr).call("node_views"))
            plan_nodes = _channels.plan_mesh_placement(
                views, num_producers=R, num_consumers=C)
        except Exception:
            logger.debug("mesh placement planning failed; leaving actor "
                         "placement to the scheduler", exc_info=True)

        def options_for(cls, opts, i, planned, hint=None):
            from ray_tpu.util.scheduling_strategies import \
                NodeAffinitySchedulingStrategy

            o = dict(opts[i]) if opts and i < len(opts) and opts[i] else {}
            if not o and hint is not None:
                # locality hint = the node_id_hex the consumer's data
                # should land on (soft: a full node falls back to the
                # scheduler and resolve_actor_placement records the miss)
                o["scheduling_strategy"] = NodeAffinitySchedulingStrategy(
                    node_id_hex=str(hint), soft=True)
            elif not o and planned is not None:
                o["scheduling_strategy"] = NodeAffinitySchedulingStrategy(
                    node_id_hex=planned[i], soft=True)
            o.setdefault("num_cpus", 0.5)
            return cls.options(**o)

        def expected(opts, i, planned, hint=None):
            # the node an affinity-scheduled actor SHOULD land on, so
            # resolve_actor_placement can record a soft-scheduling miss
            if opts and i < len(opts) and opts[i]:
                return None
            if hint is not None:
                return str(hint)
            return planned[i] if planned is not None else None

        p_nodes = plan_nodes[0] if plan_nodes else None
        c_nodes = plan_nodes[1] if plan_nodes else None
        self._expect_nodes = (
            [expected(producer_options, r, p_nodes) for r in range(R)]
            + [expected(consumer_options, c, c_nodes,
                        hint=(locality_hints[c] if locality_hints
                              else None)) for c in range(C)])

        # any mid-build failure unwinds through shutdown() — it kills
        # whatever was already created (ActorHandles have no GC-kill)
        try:
            self._producers = [
                options_for(producer_cls, producer_options, r,
                            p_nodes).remote(self._tasks, self._transform)
                for r in range(R)]
            self._consumers = [
                options_for(consumer_cls, consumer_options, c, c_nodes,
                            hint=(locality_hints[c]
                                  if locality_hints else None)).remote()
                for c in range(C)]
            ray_tpu.get([a.ping.remote() for a in self._stage_actors()],
                        timeout=180)
            sizes = ray_tpu.get(self._producers[0].probe_sizes.remote(),
                                timeout=180)
            # generous slack: frame size is bounded by min(whole block,
            # bucket_rows rows) + framing; an overflow is a loud write
            # error, and buffer_bytes= overrides when the operator knows
            # better
            frame_cap = min(
                sizes["np_bytes"],
                sizes["row_bytes"] * self._bucket_rows + 4096)
            self._frame_buffer = int(
                buffer_bytes or frame_cap * 3 // 2 + 64 * 1024)
            out_rows = self._batch_size if self._batch_size else \
                max(1, -(-T // max(1, R)))  # split mode: <= one block's rows
            self._batch_buffer = int(
                batch_buffer_bytes
                or max(sizes["row_bytes"] * out_rows, sizes["np_bytes"])
                * 3 // 2 + 64 * 1024)
            self._build_channels()
        except BaseException:
            try:
                self.shutdown()
            except Exception:
                logger.debug("exchange build unwind failed", exc_info=True)
            raise

    def _stage_actors(self):
        return list(self._producers) + list(self._consumers)

    # -- properties the probe fallback guards key on

    @property
    def is_channel_backed(self) -> bool:
        return bool(self._all_specs) and not self._dead

    @property
    def channel_depth(self) -> int:
        return self._depth

    @property
    def num_producers(self) -> int:
        return self._R

    @property
    def num_consumers(self) -> int:
        return self._C

    @property
    def epoch_stats(self) -> List[dict]:
        """Merged-mode per-epoch reports: batches, consumer stall
        seconds/fraction, the driver's control-RPC delta, per-consumer
        row counts + skew, and every stage's in-band report (incl.
        per-epoch ``rpc_calls`` — the zero-RPC proof)."""
        return list(self._epoch_stats)

    def rank_epoch_stats(self, rank: int) -> List[dict]:
        """Per-epoch reports of one consumer rank's stream."""
        return list(self._rank_epoch_stats[rank])

    # -- build

    def _create_channel(self, node_addr, participants, *,
                        buffer: int) -> _channels.ChannelSpec:
        core = self._core
        spec = _channels.create_channel(
            core, node_addr, buffer, self._depth, 1, participants)
        self._all_specs.append(spec)
        if tuple(node_addr) == tuple(core.supervisor_addr):
            self._local_channels[spec.key()] = _channels.LocalChannel(
                core.arena, spec)
        return spec

    def _build_channels(self) -> None:
        core = self._core
        driver_node = tuple(core.supervisor_addr)
        ctrl = core.clients.get(core.controller_addr)
        views = core._run(ctrl.call("node_views"))
        for a, exp in zip(self._stage_actors(), self._expect_nodes):
            hexid = a._actor_id.hex()
            self._actor_info[hexid] = _channels.resolve_actor_placement(
                core, a._actor_id, views, expect_node_id_hex=exp)

        # the mesh is one dataflow: every consumer needs every producer
        # and the driver needs every consumer, so no subset can make
        # progress alone — ANY participant's death closes every channel
        participants = {core._store_client_id}
        for info in self._actor_info.values():
            participants.add(info["worker_id_hex"])
            participants.add(f"node:{info['node_id_hex']}")

        def node_of(actor):
            return self._actor_info[actor._actor_id.hex()]["node_addr"]

        # R x C bucket-frame channels, each on its CONSUMER's (reader's)
        # node: same-node producers hit the seqlock directly, cross-node
        # producers push chunked mirror frames
        self._mesh_specs = [
            [self._create_channel(node_of(self._consumers[c]),
                                  participants, buffer=self._frame_buffer)
             for c in range(self._C)]
            for _r in range(self._R)]
        # C consumer->driver output channels on the driver's node
        self._out_specs = [
            self._create_channel(driver_node, participants,
                                 buffer=self._batch_buffer)
            for _c in range(self._C)]
        self._out_chs = [self._local_channels[s.key()]
                         for s in self._out_specs]

        for hexid in self._actor_info:
            core.subscribe("actor:" + hexid, self._on_actor_update)

        for r, actor in enumerate(self._producers):
            self._loop_refs.append(actor.run_loop.remote(_ProducerPlan(
                out_specs=[self._mesh_specs[r][c] for c in range(self._C)],
                rank=r, num_producers=self._R, num_consumers=self._C,
                num_tasks=self._T, order_seed=self._seed, kind=self._kind,
                partition_seed=self._part_seed, epochs=self._epochs,
                bucket_rows=self._bucket_rows)))
        for c, actor in enumerate(self._consumers):
            self._loop_refs.append(actor.run_loop.remote(_ConsumerPlan(
                in_specs=[self._mesh_specs[r][c] for r in range(self._R)],
                out_spec=self._out_specs[c], rank=c,
                num_producers=self._R, num_consumers=self._C,
                num_tasks=self._T, order_seed=self._seed, kind=self._kind,
                partition_seed=self._part_seed, epochs=self._epochs,
                batch_size=self._batch_size, shuffle_buffer=self._shuffle,
                drop_last=self._drop_last)))

    # -- failure fan-out (the streaming executor's shape)

    def _on_actor_update(self, message) -> None:
        if self._dead or not isinstance(message, dict):
            return
        if message.get("state") in ("DEAD", "RESTARTING"):
            self._close_for_failure()

    def _close_for_failure(self) -> None:
        self._dead = True
        _channels.close_channels_nowait(
            self._core, self._local_channels.values(), self._all_specs)

    def _surface_failure(self, closed: ChannelClosedError):
        self._close_for_failure()
        _channels.surface_loop_failure(self._core, self._loop_refs, closed)

    # -- consumption

    def _read_msg(self, c: int):
        """One message off consumer c's output channel (blocking);
        returns (version, view)."""
        v = 2 * (self._m[c] + 1)
        try:
            view = self._out_chs[c].read(v)
        except ChannelClosedError as e:
            self._surface_failure(e)
        self._m[c] += 1
        return v, view

    def _claim_mode(self, mode: str) -> None:
        if self._dead:
            raise ChannelClosedError("exchange executor was torn down")
        if self._mode is not None and self._mode != mode:
            # merged and per-rank consumption share the same C channels
            # and message counters — mixing them would silently split
            # each consumer's stream between two readers
            raise RuntimeError(
                f"exchange already consumed in {self._mode!r} mode; "
                f"build a new executor for {mode!r} consumption")
        self._mode = mode

    def batches(self, copy: bool = True) -> Iterator[Dict[str, np.ndarray]]:
        """The driver-merged stream: round-robin over the C consumer
        channels (one batch per live consumer per sweep, a consumer
        dropping out of the cycle at its epoch eof) — the deterministic
        interleave ``task_exchange_batches(consumer_rank=None)``
        reproduces. ``copy=False`` yields READ-ONLY arena views, acked
        when the iterator advances."""
        self._claim_mode("merged")
        if self._exhausted:
            raise RuntimeError(
                "exchange executor already consumed; build a new one "
                "(epochs are fixed at build time)")
        if any(self._consuming):
            raise RuntimeError(
                "another batches() iterator is already consuming this "
                "executor")
        self._consuming = [True] * self._C
        try:
            yield from self._merged(copy)
        finally:
            self._consuming = [False] * self._C

    def _merged(self, copy: bool) -> Iterator[Dict[str, np.ndarray]]:
        from ray_tpu._private import rpc

        prev_rpc = rpc._m_client_calls.total()
        for epoch in range(1, self._epochs + 1):
            live = list(range(self._C))
            stage_reports: List[dict] = []
            rows_per_consumer = [0] * self._C
            batches = 0
            stall_s = 0.0
            epoch_t0 = None
            while live:
                for c in list(live):
                    t0 = time.perf_counter()
                    v, view = self._read_msg(c)
                    wait = time.perf_counter() - t0
                    if epoch_t0 is None:
                        # the first batch's wait spans mesh spin-up and
                        # driver think-time — start the epoch clock here
                        epoch_t0 = time.perf_counter()
                    else:
                        stall_s += wait
                        flight.instant(_F_STALL, int(wait * 1e6))
                    msg = serialization.unpack(view)
                    if "eof" in msg:
                        stage_reports.extend(msg["stats"])
                        rows_per_consumer[c] = msg.get("rows", 0)
                        del msg, view
                        self._out_chs[c].ack(0, v)
                        live.remove(c)
                        continue
                    batches += 1
                    if copy:
                        b = _copy_batch(msg["b"])
                        del msg, view
                        self._out_chs[c].ack(0, v)
                        yield b
                    else:
                        try:
                            yield msg["b"]
                        finally:
                            del msg, view
                            self._out_chs[c].ack(0, v)
            now = rpc._m_client_calls.total()
            wall = max(time.perf_counter() - (epoch_t0 or
                                              time.perf_counter()), 1e-9)
            mean_rows = max(sum(rows_per_consumer) / self._C, 1e-9)
            skew = max(rows_per_consumer) / mean_rows
            _m_ex_skew.set(skew)
            self._epoch_stats.append({
                "epoch": epoch, "batches": batches,
                "stall_s": stall_s,
                "stall_fraction": min(1.0, stall_s / wall),
                "consumer_rpc_calls": now - prev_rpc,
                "rows_per_consumer": rows_per_consumer,
                "skew": skew,
                "stage_reports": stage_reports,
            })
            prev_rpc = now
        self._exhausted = True

    def rank_epoch(self, rank: int, epoch: Optional[int] = None,
                   copy: bool = True) -> Iterator[Dict[str, np.ndarray]]:
        """ONE epoch of one consumer rank's stream (the streaming_split
        per-rank iterator's unit): reads that rank's output channel up
        to its epoch eof. Epochs must be consumed in order."""
        self._claim_mode("ranks")
        c = int(rank)
        expected = self._rank_epoch_done[c] + 1
        if epoch is None:
            epoch = expected
        if epoch != expected:
            raise RuntimeError(
                f"exchange rank {c} epochs are consumed in order: "
                f"expected epoch {expected}, got {epoch} "
                f"(built with epochs={self._epochs})")
        if epoch > self._epochs:
            raise RuntimeError(
                f"exchange rank {c} exhausted its {self._epochs} "
                f"epoch(s); build with epochs=")
        if self._consuming[c]:
            raise RuntimeError(
                f"another iterator is already consuming exchange "
                f"rank {c}")
        self._consuming[c] = True
        try:
            yield from self._rank_epoch(c, epoch, copy)
        finally:
            self._consuming[c] = False

    def _rank_epoch(self, c: int, epoch: int,
                    copy: bool) -> Iterator[Dict[str, np.ndarray]]:
        from ray_tpu._private import rpc

        prev_rpc = rpc._m_client_calls.total()
        batches = 0
        stall_s = 0.0
        epoch_t0 = None
        while True:
            t0 = time.perf_counter()
            v, view = self._read_msg(c)
            wait = time.perf_counter() - t0
            if epoch_t0 is None:
                epoch_t0 = time.perf_counter()
            else:
                stall_s += wait
                flight.instant(_F_STALL, int(wait * 1e6))
            msg = serialization.unpack(view)
            if "eof" in msg:
                stats = list(msg["stats"])
                rows = msg.get("rows", 0)
                del msg, view
                self._out_chs[c].ack(0, v)
                now = rpc._m_client_calls.total()
                wall = max(time.perf_counter() - epoch_t0, 1e-9)
                self._rank_epoch_stats[c].append({
                    "epoch": epoch, "batches": batches, "rows": rows,
                    "stall_s": stall_s,
                    "stall_fraction": min(1.0, stall_s / wall),
                    "consumer_rpc_calls": now - prev_rpc,
                    "stage_reports": stats,
                })
                self._rank_epoch_done[c] = epoch
                return
            batches += 1
            if copy:
                b = _copy_batch(msg["b"])
                del msg, view
                self._out_chs[c].ack(0, v)
                yield b
            else:
                try:
                    yield msg["b"]
                finally:
                    del msg, view
                    self._out_chs[c].ack(0, v)

    def rank_batches(self, rank: int,
                     copy: bool = True) -> Iterator[Dict[str, np.ndarray]]:
        """Every epoch of one consumer rank's stream, back to back —
        what a PipelineTrainer dp rank consumes."""
        for epoch in range(1, self._epochs + 1):
            yield from self.rank_epoch(rank, epoch, copy)

    def feed(self, step: Callable[[Dict[str, np.ndarray]], Any], *,
             rank: Optional[int] = None) -> Iterator[Any]:
        """Hand every batch straight to a trainer step as read-only
        arena views — the batch never leaves the arena; the channel slot
        is acked after the step returns. ``rank=r`` feeds one dp rank
        from ITS OWN consumer's output (each rank of a dp trainer runs
        its own feed); ``rank=None`` feeds the merged stream. Yields
        each step's result."""
        src = (self.batches(copy=False) if rank is None
               else self.rank_batches(rank, copy=False))
        for batch in src:
            yield step(batch)

    # -- teardown

    def shutdown(self, kill_actors: bool = True,
                 timeout: float = 30) -> Dict[str, Any]:
        """Close every channel of the mesh, drain the stage loops,
        release the pins, (optionally) kill the stage actors.
        Idempotent."""
        self._dead = True
        with self._teardown_lock:
            if self._torn:
                return {}
            self._torn = True
        core = self._core
        for ch in self._local_channels.values():
            try:
                ch.close()
            except Exception:
                pass
        for hexid in self._actor_info:
            try:
                core.unsubscribe("actor:" + hexid, self._on_actor_update)
            except Exception:
                pass
        _channels.close_specs(core, self._all_specs)
        stats: Dict[str, Any] = {"loops": []}
        for ref in self._loop_refs:
            try:
                stats["loops"].append(core.get([ref], timeout=timeout)[0])
            except Exception:
                stats["loops"].append(None)
        _channels.free_and_unpin_specs(core, self._all_specs)
        if kill_actors:
            for a in self._stage_actors():
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass
        return stats

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


class ExchangeBatches:
    """The iterator `Dataset.stream_batches` returns for exchange plans:
    owns an ExchangeExecutor, yields its merged batches, and shuts it
    down on exhaustion or early close (a `break` releases the
    actors/pins)."""

    def __init__(self, ops, **kw):
        self.executor = ExchangeExecutor(ops, **kw)

    @property
    def epoch_stats(self) -> List[dict]:
        return self.executor.epoch_stats

    def __iter__(self):
        try:
            yield from self.executor.batches()
        finally:
            self.executor.shutdown()
