"""Streaming plan executor.

Analog of the reference's `python/ray/data/_internal/execution/
streaming_executor.py:48` + `operators/task_pool_map_operator.py`, reshaped
around this runtime's dataflow: map stages submit block tasks with a
bounded in-flight window and *yield refs downstream without waiting* — the
object layer's task-arg resolution does the waiting, so the whole pipeline
stays dataflow-driven and backpressure comes from generator laziness (the
consumer pulls; each stage holds at most `concurrency` pending tasks).

All-to-all ops (repartition / random_shuffle / sort / groupby) are
barriers implemented as two-phase distributed shuffles: phase 1 splits each
input block into n parts (one task per block, num_returns=n), phase 2
builds each output partition from its parts (one task per output) — the
reference's push-based shuffle (`_internal/planner/exchange/`).
"""

from __future__ import annotations

import itertools
import logging
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data._internal.logical import (ActorPoolMap, AllToAll, InputData,
                                            Limit, LogicalOp, OneToOne, Read,
                                            Union, Zip, fuse_transforms)
from ray_tpu.data.block import (Block, block_meta, concat_blocks, slice_block)

logger = logging.getLogger(__name__)

DEFAULT_CONCURRENCY = 8
# memory-aware backpressure: a stage narrows its in-flight window when
# observed block sizes would put more than this many bytes in flight
# (≈ the reference's resource-budget backpressure, streaming_executor.py:48)
DEFAULT_MAX_BYTES_IN_FLIGHT = 256 * 1024 * 1024

# ---------------------------------------------------------------- task fns


def _run_read(task) -> Tuple[Block, Dict]:
    b = task()
    return b, block_meta(b)


def _run_transform(transform, block) -> Tuple[Block, Dict]:
    out = transform(block)
    return out, block_meta(out)


def _run_slice(block, start, end) -> Tuple[Block, Dict]:
    out = slice_block(block, start, end)
    return out, block_meta(out)


def _slice_concat(spec, *blocks) -> Tuple[Block, Dict]:
    """spec: list of (block_index, start, end) into `blocks`."""
    out = concat_blocks([slice_block(blocks[j], s, e) for j, s, e in spec])
    return out, block_meta(out)


def _split_random(block, n, seed) -> List[Block]:
    rng = np.random.default_rng(seed)
    assignment = rng.integers(0, n, size=block.num_rows)
    import pyarrow as pa

    return [block.filter(pa.array(assignment == i)) for i in range(n)]


def _split_by_bounds(block, key, bounds, descending) -> List[Block]:
    import pyarrow as pa

    col = block.column(key).to_numpy(zero_copy_only=False)
    part = np.searchsorted(np.asarray(bounds), col, side="right")
    n = len(bounds) + 1
    if descending:
        part = (n - 1) - part
    return [block.filter(pa.array(part == i)) for i in range(n)]


def _stable_hash(v) -> int:
    """Process-independent hash: builtin hash() is salted per process for
    str/bytes, which would scatter one key across partitions when blocks
    are split by different workers."""
    import zlib

    return zlib.crc32(repr(v).encode())


def _split_by_hash(block, key, n) -> List[Block]:
    import pyarrow as pa

    col = block.column(key).to_pylist()
    part = np.fromiter((_stable_hash(v) % n for v in col), dtype=np.int64,
                       count=len(col))
    return [block.filter(pa.array(part == i)) for i in range(n)]


def _concat_shuffled(seed, *parts) -> Tuple[Block, Dict]:
    out = concat_blocks(list(parts))
    if out.num_rows:
        rng = np.random.default_rng(seed)
        out = out.take(rng.permutation(out.num_rows))
    return out, block_meta(out)


def _concat_sorted(key, descending, *parts) -> Tuple[Block, Dict]:
    out = concat_blocks(list(parts))
    if out.num_rows:
        out = out.sort_by([(key, "descending" if descending else "ascending")])
    return out, block_meta(out)


def _concat_grouped(agg_fn, *parts) -> Tuple[Block, Dict]:
    from ray_tpu.data.block import batch_to_block, block_to_batch

    merged = concat_blocks(list(parts))
    if merged.num_rows == 0:
        return merged, block_meta(merged)
    out = batch_to_block(agg_fn(block_to_batch(merged, "pandas")))
    return out, block_meta(out)


def _sample_column(block, key, k=64) -> list:
    col = block.column(key).to_numpy(zero_copy_only=False)
    if len(col) == 0:
        return []
    idx = np.linspace(0, len(col) - 1, min(k, len(col))).astype(int)
    return list(col[idx])


def _zip_blocks(left, right) -> Tuple[Block, Dict]:
    import pyarrow as pa

    assert left.num_rows == right.num_rows
    cols = {name: left.column(name) for name in left.column_names}
    for name in right.column_names:
        out_name = name if name not in cols else name + "_1"
        cols[out_name] = right.column(name)
    out = pa.table(cols)
    return out, block_meta(out)


_read_r = ray_tpu.remote(_run_read)
_xform_r = ray_tpu.remote(_run_transform)
_slice_r = ray_tpu.remote(_run_slice)
_slice_concat_r = ray_tpu.remote(_slice_concat)
_split_random_r = ray_tpu.remote(_split_random)
_split_bounds_r = ray_tpu.remote(_split_by_bounds)
_split_hash_r = ray_tpu.remote(_split_by_hash)
_concat_shuffled_r = ray_tpu.remote(_concat_shuffled)
_concat_sorted_r = ray_tpu.remote(_concat_sorted)
_concat_grouped_r = ray_tpu.remote(_concat_grouped)
_sample_r = ray_tpu.remote(_sample_column)
_zip_r = ray_tpu.remote(_zip_blocks)

RefMeta = Tuple[Any, Any]  # (block ref, meta dict-or-ref)


def resolve_meta(meta) -> Dict[str, Any]:
    return meta if isinstance(meta, dict) else ray_tpu.get(meta)


# ------------------------------------------------------------------ stages


def _windowed(submit: Callable[[Any], RefMeta], upstream: Iterator,
              concurrency: int,
              max_bytes: int = DEFAULT_MAX_BYTES_IN_FLIGHT) -> Iterator[RefMeta]:
    """Bounded in-flight submission window with byte-aware backpressure:
    the effective window shrinks below `concurrency` when the running
    average block size implies more than `max_bytes` in flight."""
    window: deque = deque()
    bytes_seen = 0
    blocks_seen = 0

    def pop() -> RefMeta:
        nonlocal bytes_seen, blocks_seen
        ref, meta = window.popleft()
        m = resolve_meta(meta)
        bytes_seen += m.get("size_bytes", 0) or 0
        blocks_seen += 1
        return ref, m

    for item in upstream:
        effective = concurrency
        if blocks_seen:
            avg = max(1.0, bytes_seen / blocks_seen)
            effective = max(1, min(concurrency, int(max_bytes / avg)))
        while len(window) >= effective:
            yield pop()
        window.append(submit(item))
    while window:
        yield pop()


class ReadStage:
    def __init__(self, read_tasks, concurrency):
        self.read_tasks = read_tasks
        self.concurrency = concurrency

    def run(self, _upstream) -> Iterator[RefMeta]:
        def submit(task):
            r = _read_r.options(num_returns=2).remote(task)
            return (r[0], r[1])

        return _windowed(submit, iter(self.read_tasks), self.concurrency)


class MapStage:
    def __init__(self, transform, concurrency, num_cpus: float = None):
        self.transform = transform
        self.concurrency = concurrency
        self.num_cpus = num_cpus

    def run(self, upstream) -> Iterator[RefMeta]:
        opts = {"num_returns": 2}
        if self.num_cpus is not None:
            opts["num_cpus"] = self.num_cpus

        def submit(pair):
            r = _xform_r.options(**opts).remote(self.transform, pair[0])
            return (r[0], r[1])

        return _windowed(submit, upstream, self.concurrency)


class _MapWorker:
    """Actor body for stateful batch UDFs: constructs the callable once,
    then maps blocks through it (≈ _MapWorker in
    actor_pool_map_operator.py)."""

    def __init__(self, fn_cls, ctor_args, ctor_kwargs, batch_size,
                 batch_format, fn_args, fn_kwargs):
        from ray_tpu.data._internal.logical import make_map_batches_transform

        self._fn = fn_cls(*ctor_args, **(ctor_kwargs or {}))
        self._transform = make_map_batches_transform(
            self._fn, batch_size, batch_format, fn_args, fn_kwargs)

    def apply(self, block):
        out = self._transform(block)
        return out, block_meta(out)


class ActorPoolMapStage:
    """Streams blocks through a fixed pool of stateful map actors with a
    bounded per-actor in-flight window; output order == input order."""

    def __init__(self, op: ActorPoolMap):
        self.op = op

    def run(self, upstream) -> Iterator[RefMeta]:
        op = self.op
        worker_cls = ray_tpu.remote(_MapWorker)
        pool = [
            worker_cls.options(num_cpus=op.num_cpus).remote(
                op.fn_cls, op.fn_constructor_args, op.fn_constructor_kwargs,
                op.batch_size, op.batch_format, op.fn_args, op.fn_kwargs)
            for _ in range(op.pool_size)
        ]
        inflight = [0] * len(pool)
        window: deque = deque()  # (ref, meta_ref, actor_idx)
        cap = op.pool_size * op.max_tasks_in_flight_per_actor

        def pop() -> RefMeta:
            ref, meta, idx = window.popleft()
            m = resolve_meta(meta)  # blocks until that actor finished it
            inflight[idx] -= 1
            return ref, m

        try:
            for ref, _meta in upstream:
                while len(window) >= cap:
                    yield pop()
                idx = min(range(len(pool)), key=lambda i: inflight[i])
                r = pool[idx].apply.options(num_returns=2).remote(ref)
                inflight[idx] += 1
                window.append((r[0], r[1], idx))
            while window:
                yield pop()
        finally:
            for a in pool:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass


class LimitStage:
    def __init__(self, n):
        self.n = n

    def run(self, upstream) -> Iterator[RefMeta]:
        remaining = self.n
        if remaining <= 0:
            return
        for ref, meta in upstream:
            m = resolve_meta(meta)
            rows = m["num_rows"]
            if rows <= remaining:
                remaining -= rows
                yield ref, m
            else:
                r = _slice_r.options(num_returns=2).remote(ref, 0, remaining)
                yield r[0], r[1]
                remaining = 0
            # stop before pulling (and thereby submitting) another block
            if remaining <= 0:
                break


class AllToAllStage:
    def __init__(self, kind: str, args: Dict[str, Any], concurrency: int):
        self.kind = kind
        self.args = args
        self.concurrency = concurrency

    def run(self, upstream) -> Iterator[RefMeta]:
        if self.kind in ("shuffle", "repartition"):
            # surfaced, never silent: this is the materializing slow
            # path — seeded shuffle/repartition plans stream through the
            # channel exchange via iter_batches(streaming=True) /
            # streaming_split instead of stalling at this barrier
            logger.info(
                "AllToAll %r running as a task-executor BARRIER "
                "(every upstream block materializes in the object "
                "store); the streaming exchange "
                "(data/_internal/exchange.py) runs it as channel "
                "stages", self.kind)
        pairs = list(upstream)  # barrier: consume the whole upstream
        refs = [p[0] for p in pairs]
        metas = [resolve_meta(p[1]) for p in pairs]
        yield from getattr(self, "_" + self.kind)(refs, metas)

    def _out_count(self, refs) -> int:
        n = self.args.get("num_blocks")
        return max(1, n if n else len(refs))

    def _repartition(self, refs, metas) -> Iterator[RefMeta]:
        n = self._out_count(refs)
        rows = [m["num_rows"] for m in metas]
        total = sum(rows)
        # global row offsets of each output partition
        cuts = [round(i * total / n) for i in range(n + 1)]
        starts = np.cumsum([0] + rows)
        for i in range(n):
            lo, hi = cuts[i], cuts[i + 1]
            spec, needed = [], []
            for j, m in enumerate(metas):
                b0, b1 = starts[j], starts[j + 1]
                s, e = max(lo, b0), min(hi, b1)
                if s < e:
                    spec.append((len(needed), int(s - b0), int(e - b0)))
                    needed.append(refs[j])
            r = _slice_concat_r.options(num_returns=2).remote(spec, *needed)
            yield r[0], r[1]

    def _shuffle(self, refs, metas) -> Iterator[RefMeta]:
        n = self._out_count(refs)
        seed = self.args.get("seed")
        base = seed if seed is not None else np.random.randint(0, 2**31)
        parts = [
            _split_random_r.options(num_returns=n).remote(ref, n, base + i)
            if n > 1 else [ref]
            for i, ref in enumerate(refs)
        ]
        for j in range(n):
            mine = [parts[i][j] for i in range(len(refs))]
            r = _concat_shuffled_r.options(num_returns=2).remote(
                base + 7919 + j, *mine)
            yield r[0], r[1]

    def _sort(self, refs, metas) -> Iterator[RefMeta]:
        key = self.args["key"]
        descending = self.args.get("descending", False)
        n = self._out_count(refs)
        if n > 1:
            samples = sorted(
                itertools.chain.from_iterable(
                    ray_tpu.get([_sample_r.remote(r, key) for r in refs])))
            if samples:
                q = np.linspace(0, len(samples) - 1, n + 1).astype(int)[1:-1]
                bounds = [samples[i] for i in q]
            else:
                bounds = []
            if not bounds:
                n, bounds = 1, []
        else:
            bounds = []
        if n == 1:
            r = _concat_sorted_r.options(num_returns=2).remote(
                key, descending, *refs)
            yield r[0], r[1]
            return
        parts = [
            _split_bounds_r.options(num_returns=len(bounds) + 1).remote(
                ref, key, bounds, descending)
            for ref in refs
        ]
        for j in range(len(bounds) + 1):
            mine = [parts[i][j] for i in range(len(refs))]
            r = _concat_sorted_r.options(num_returns=2).remote(
                key, descending, *mine)
            yield r[0], r[1]

    def _groupby(self, refs, metas) -> Iterator[RefMeta]:
        key = self.args["key"]
        agg_fn = self.args["agg_fn"]
        n = min(self._out_count(refs), max(1, len(refs)))
        if n == 1:
            parts = [[r] for r in refs]
        else:
            parts = [
                _split_hash_r.options(num_returns=n).remote(ref, key, n)
                for ref in refs
            ]
        for j in range(n):
            mine = [parts[i][j] if n > 1 else parts[i][0]
                    for i in range(len(refs))]
            r = _concat_grouped_r.options(num_returns=2).remote(agg_fn, *mine)
            yield r[0], r[1]


class ZipStage:
    def __init__(self, other_ops: List[LogicalOp], concurrency: int):
        self.other_ops = other_ops
        self.concurrency = concurrency

    def run(self, upstream) -> Iterator[RefMeta]:
        left = list(upstream)
        right = list(execute_plan(self.other_ops, self.concurrency))
        l_metas = [resolve_meta(m) for _, m in left]
        r_metas = [resolve_meta(m) for _, m in right]
        if sum(m["num_rows"] for m in l_metas) != sum(
                m["num_rows"] for m in r_metas):
            raise ValueError("zip: datasets have different row counts")
        # align right side to left's block row layout
        r_refs = [r for r, _ in right]
        r_rows = [m["num_rows"] for m in r_metas]
        r_starts = np.cumsum([0] + r_rows)
        offset = 0
        for (l_ref, l_meta), lm in zip(left, l_metas):
            lo, hi = offset, offset + lm["num_rows"]
            spec, needed = [], []
            for j in range(len(r_refs)):
                b0, b1 = r_starts[j], r_starts[j + 1]
                s, e = max(lo, b0), min(hi, b1)
                if s < e:
                    spec.append((len(needed), int(s - b0), int(e - b0)))
                    needed.append(r_refs[j])
            aligned = _slice_concat_r.options(num_returns=2).remote(
                spec, *needed)
            r = _zip_r.options(num_returns=2).remote(l_ref, aligned[0])
            yield r[0], r[1]
            offset = hi


# --------------------------------------------------------------- pipeline


def execute_plan(ops: List[LogicalOp],
                 concurrency: int = DEFAULT_CONCURRENCY) -> Iterator[RefMeta]:
    """Compile the logical chain into stages and return the output stream.

    The stream is built op by op so stages that follow a Union apply to the
    combined stream, not just the left branch. Everything stays lazy: no
    task is submitted until the returned iterator is pulled.
    """
    if not ops:
        return iter(())
    source = ops[0]
    if isinstance(source, InputData):
        stream: Iterator[RefMeta] = iter(list(zip(source.block_refs,
                                                  source.metas)))
    elif isinstance(source, Read):
        stream = ReadStage(source.read_tasks, concurrency).run(None)
    else:
        raise TypeError(f"plan must start with a source, got {source!r}")

    pending_transforms: List[Any] = []

    def flush(s: Iterator[RefMeta]) -> Iterator[RefMeta]:
        if pending_transforms:
            s = MapStage(fuse_transforms(list(pending_transforms)),
                         concurrency).run(s)
            pending_transforms.clear()
        return s

    for op in ops[1:]:
        if isinstance(op, OneToOne) and op.concurrency is None \
                and op.num_cpus is None:
            pending_transforms.append(op.transform)
        elif isinstance(op, OneToOne):
            # explicit per-op concurrency/resources: own stage, not fused
            stream = MapStage(op.transform, op.concurrency or concurrency,
                              num_cpus=op.num_cpus).run(flush(stream))
        elif isinstance(op, ActorPoolMap):
            stream = ActorPoolMapStage(op).run(flush(stream))
        elif isinstance(op, Limit):
            stream = LimitStage(op.n).run(flush(stream))
        elif isinstance(op, AllToAll):
            stream = AllToAllStage(op.kind, op.args, concurrency).run(
                flush(stream))
        elif isinstance(op, Zip):
            stream = ZipStage(op.other, concurrency).run(flush(stream))
        elif isinstance(op, Union):
            stream = itertools.chain(
                flush(stream),
                *[execute_plan(t, concurrency) for t in op.others])
        else:
            raise TypeError(f"unexpected logical op {op!r}")
    return flush(stream)
