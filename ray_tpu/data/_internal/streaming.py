"""Streaming data plane: compiled ingest pipelines over channels.

The task-based executor (`executor.py`) moves every block through
task-by-task object-store hops: per block, a task submission RPC, a store
put, a locate + get round trip — a control/data-plane cost that scales
with the block count and stalls a fast consumer at every boundary. This
module rebuilds ingest the way `train.PipelineTrainer` rebuilt training:
a fixed stage graph of long-lived actors connected by depth-k slot-ring
channels (`_private/channels.py`, the PR-8 protocol), planned once at
build time, streaming thereafter with ZERO steady-state control-plane
RPCs per stage and per consumer (counter-proven via the
``ray_tpu_rpc_client_calls_total`` deltas each epoch report carries —
the PR-3 idiom).

Topology::

    R shard readers --> R transform actors --> 1 batcher --> consumer
        (lazy read tasks)   (fused map chain)    (shuffle+batch)

* every edge is one channel placed on the READER's node: same-node hops
  are zero-copy arena seqlock ops, cross-node hops are chunked mirror
  pushes (the PR-2 bounded transfer window);
* channel depth = the prefetch bound: a stage can run at most ``depth``
  blocks/batches ahead of its consumer — writer backpressure IS the
  prefetch limit (``RAY_TPU_DATA_STREAM_DEPTH``);
* the batcher re-chunks blocks into FIXED-SHAPE batches (optionally
  through a seeded windowed shuffle buffer) and commits them into the
  consumer channel; ``Dataset.stream_batches`` / ``iter_batches(
  streaming=True)`` is one channel read per batch.

Epoch semantics: the shard (read-task) order is re-seeded per epoch —
``epoch_order(T, seed, epoch)`` — and every participant derives it
locally, so an epoch boundary costs zero control messages. Reader r
executes ``order[r::R]`` in order and the batcher interleaves its
upstreams round-robin, which reconstructs the global order EXACTLY; the
windowed shuffle + fixed-shape batching then run through the SAME code
(`epoch_batch_stream`) the task-based baseline uses, so a streaming
epoch is batch-for-batch, bit-for-bit identical to the task loader's at
the same seed — shuffled or not. ``task_epoch_batches`` IS that
baseline (real remote read/transform tasks through the object store —
the ``algo="kv"`` idiom: a measured comparison target, never a silent
fallback; streaming build failures raise).

Failure semantics follow the house pattern: teardown or ANY
participant's death closes every channel (supervisor participant
registry + driver-side actor-state subscription), blocked peers raise
``ChannelClosedError`` instead of hanging, pins return to baseline, and
a partially-consumed epoch surfaces a clean error — never a silently
truncated epoch.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from ray_tpu._private import channels as _channels
from ray_tpu._private import chaos, flight, serialization
from ray_tpu._private.exceptions import ChannelClosedError
from ray_tpu._private.metrics import Counter, Gauge

logger = logging.getLogger(__name__)

# flight-recorder span ids for the ingest hot loop (per-thread ring
# records — no locks, no RPCs, so the zero-RPC proofs hold recorder-on)
_F_READ = flight.intern("data.read")
_F_TRANSFORM = flight.intern("data.transform")
_F_BATCH = flight.intern("data.batch")
_F_STALL = flight.intern("data.stall")

_m_blocks = Counter(
    "ray_tpu_data_blocks_read_total",
    "Streaming data plane: blocks produced by shard readers")
_m_batches = Counter(
    "ray_tpu_data_batches_out_total",
    "Streaming data plane: fixed-shape batches committed by the batcher")
_m_stall = Counter(
    "ray_tpu_data_stall_seconds_total",
    "Streaming data plane: seconds the consumer spent blocked waiting "
    "for the next batch (input-bound time, measured not estimated)")
_m_depth = Gauge(
    "ray_tpu_data_stream_depth",
    "Slot-ring depth (prefetch bound) of the most recently built live "
    "streaming pipeline; 0 when none is live in this process")

# live-executor accounting behind the gauge: last build wins while any
# pipeline lives, and the gauge drops to 0 when the last one tears down
_live_lock = threading.Lock()
_live_executors = 0


def _require_positive(name: str, value, kind=int):
    """Explicit zeros (and negatives) RAISE instead of falling through a
    falsy-``or`` chain to a default — the PR-8 depth=0 lesson."""
    if value is None:
        raise ValueError(f"{name} must be set")
    v = kind(value)
    if v <= 0:
        raise ValueError(
            f"{name} must be a positive {kind.__name__}, got {value!r} "
            f"(explicit zeros are rejected, never silently replaced "
            f"with a default)")
    return v


def _env_stream_depth(config) -> int:
    """Stream depth from config, rejecting an explicit env zero loudly
    (``Config.from_env`` would otherwise hand the 0 straight through and
    ``channel_create`` would reject it with a far less useful error)."""
    raw = os.environ.get("RAY_TPU_DATA_STREAM_DEPTH")
    if raw is not None and int(raw) <= 0:
        raise ValueError(
            f"RAY_TPU_DATA_STREAM_DEPTH={raw!r}: explicit zeros are "
            f"rejected (unset the var for the default)")
    return _require_positive("data_stream_depth", config.data_stream_depth)


def _default_shuffle(config) -> Optional[int]:
    """Default shuffle-buffer rows from ``Config.data_shuffle_buffer``
    (so programmatic ``_system_config`` overrides work like every other
    knob): 0 -> None (no shuffle, the field default), positive -> that
    many rows — but an EXPLICIT ``RAY_TPU_DATA_SHUFFLE_BUFFER=0`` env
    raises rather than silently meaning "off"."""
    raw = os.environ.get("RAY_TPU_DATA_SHUFFLE_BUFFER")
    if raw is not None and int(raw) <= 0:
        raise ValueError(
            f"RAY_TPU_DATA_SHUFFLE_BUFFER={raw!r}: explicit zeros are "
            f"rejected (unset the var to disable the shuffle)")
    rows = int(config.data_shuffle_buffer)
    if rows < 0:
        raise ValueError(
            f"data_shuffle_buffer must be >= 0, got {rows}")
    return rows or None


def quiesce_driver_rpcs(timeout_s: float = 5.0) -> None:
    """Drain the driver's background pin-release traffic before a
    zero-RPC assertion window: zero-copy views from earlier task-path
    work release their pins via GC finalizers -> batched unpin RPCs,
    which would otherwise trickle into the consumer's process-wide
    rpc-counter delta and read as steady-state traffic."""
    import gc

    from ray_tpu._private import api

    core = api._require_core()
    gc.collect()
    deadline = time.monotonic() + timeout_s
    while (core._unpin_queue or core._unpin_flushing) \
            and time.monotonic() < deadline:
        time.sleep(0.02)


# ------------------------------------------------------- epoch determinism


def epoch_order(num_shards: int, seed: Optional[int],
                epoch: int) -> np.ndarray:
    """The shard (read-task) order of one epoch: a permutation re-seeded
    per (seed, epoch), derived locally by every stage — an epoch boundary
    needs no control message. ``seed=None`` keeps the plan order every
    epoch (the task executor's order)."""
    if seed is None:
        return np.arange(num_shards)
    rng = np.random.default_rng([int(seed) & 0x7FFFFFFF, 0x5EED, int(epoch)])
    return rng.permutation(num_shards)


def shuffle_rng(seed: Optional[int], epoch: int,
                rank: int = 0) -> np.random.Generator:
    """The windowed-shuffle RNG of one epoch — shared by the batcher
    stage and the task-based baseline so shuffled epochs stay
    batch-for-batch identical. An explicit seed is REQUIRED: silently
    substituting a fixed seed would make every "unseeded" run's shuffle
    bit-identical across restarts (worse than no shuffle entropy), and
    substituting fresh entropy would break the streaming/task parity
    contract.

    ``rank``: the exchange's per-consumer stream index — each consumer
    rank draws an independent rng stream. rank 0 keeps the original key
    (single-batcher sequences are unchanged)."""
    if seed is None:
        raise ValueError(
            "the windowed shuffle buffer needs an explicit seed "
            "(pass seed=/local_shuffle_seed=; the shuffle is derived "
            "per-epoch from (seed, epoch))")
    key = [int(seed) & 0x7FFFFFFF, 0xBA7C, int(epoch)]
    if rank:
        key.append(int(rank))
    return np.random.default_rng(key)


# --------------------------------------------- numpy-batch stream plumbing


def _np_rows(batch: Dict[str, np.ndarray]) -> int:
    for v in batch.values():
        return len(v)
    return 0


def _np_concat(batches: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    if len(batches) == 1:
        return batches[0]
    keys = batches[0].keys()
    return {k: np.concatenate([b[k] for b in batches]) for k in keys}


def _np_slice(batch: Dict[str, np.ndarray], lo: int,
              hi: int) -> Dict[str, np.ndarray]:
    return {k: v[lo:hi] for k, v in batch.items()}


def _np_take(batch: Dict[str, np.ndarray], idx) -> Dict[str, np.ndarray]:
    return {k: v[idx] for k, v in batch.items()}


def _shuffle_np_stream(blocks: Iterator[Dict[str, np.ndarray]],
                       buffer_rows: int,
                       rng: np.random.Generator
                       ) -> Iterator[Dict[str, np.ndarray]]:
    """Windowed shuffle over numpy-dict blocks — the `_shuffle_blocks`
    schedule (fill to buffer_rows, permute, emit half, keep half) with
    the SAME rng draw sequence on both the streaming batcher and the
    task baseline."""
    buf: List[Dict[str, np.ndarray]] = []
    rows = 0
    for b in blocks:
        buf.append(b)
        rows += _np_rows(b)
        if rows >= buffer_rows:
            merged = _np_take(_np_concat(buf), rng.permutation(rows))
            half = rows // 2
            yield _np_slice(merged, 0, half)
            buf, rows = [_np_slice(merged, half, rows)], rows - half
    if buf:
        merged = _np_concat(buf)
        n = _np_rows(merged)
        if n:
            yield _np_take(merged, rng.permutation(n))


def epoch_batch_stream(blocks: Iterator[Dict[str, np.ndarray]], *,
                       batch_size: int,
                       shuffle_buffer: Optional[int] = None,
                       rng: Optional[np.random.Generator] = None,
                       drop_last: bool = False
                       ) -> Iterator[Dict[str, np.ndarray]]:
    """Numpy-dict blocks -> fixed-shape ``batch_size``-row batches,
    optionally through the windowed shuffle. The ONE implementation both
    the streaming batcher stage and the task-based baseline run, so
    exact batch parity holds by construction."""
    if shuffle_buffer:
        if rng is None:
            raise ValueError("shuffle_buffer needs a seeded rng")
        blocks = _shuffle_np_stream(blocks, int(shuffle_buffer), rng)
    carry: List[Dict[str, np.ndarray]] = []
    rows = 0
    for b in blocks:
        n = _np_rows(b)
        if n == 0:
            continue
        carry.append(b)
        rows += n
        while rows >= batch_size:
            merged = _np_concat(carry)
            yield _np_slice(merged, 0, batch_size)
            carry = [_np_slice(merged, batch_size, rows)]
            rows -= batch_size
    if rows > 0 and not drop_last:
        yield _np_concat(carry)


def _copy_batch(batch):
    """Deep-copy ndarray leaves out of the shared arena so the channel
    can be acked while the value lives on (the pipeline loop's rule)."""
    if isinstance(batch, np.ndarray):
        return np.array(batch)
    if isinstance(batch, dict):
        return {k: _copy_batch(v) for k, v in batch.items()}
    if isinstance(batch, (list, tuple)):
        return type(batch)(_copy_batch(v) for v in batch)
    return batch


# -------------------------------------------------------- plan validation


def split_streamable_plan(ops):
    """(read_tasks, fused_transform_or_None) of a streamable plan.

    Streaming executes read -> map chains (the ingest shape); plans that
    need a barrier or pre-materialized refs raise with a pointer at the
    task-based executor — never a silent fallback."""
    from ray_tpu.data._internal import logical as L

    if not ops:
        raise ValueError("empty plan")
    src = ops[0]
    if not isinstance(src, L.Read):
        raise ValueError(
            f"streaming execution needs a lazy Read source "
            f"(ray_tpu.data.range / read_parquet / ...), got "
            f"{type(src).__name__}; materialized datasets run on the "
            f"task-based executor (iter_batches without streaming=True)")
    transforms = []
    for op in ops[1:]:
        if isinstance(op, L.OneToOne):
            transforms.append(op.transform)
        else:
            raise ValueError(
                f"streaming execution supports read->map chains only; "
                f"{type(op).__name__} is a barrier/stateful op — use the "
                f"task-based executor (iter_batches without "
                f"streaming=True)")
    tasks = list(src.read_tasks)
    if not tasks:
        raise ValueError("streaming execution needs >= 1 read task")
    fused = L.fuse_transforms(transforms) if transforms else None
    return tasks, fused


# --------------------------------------------------- task-based baseline


def task_epoch_batches(ops, *, batch_size: int, epoch: int = 1,
                       seed: Optional[int] = 0,
                       shuffle_buffer: Optional[int] = None,
                       drop_last: bool = False,
                       concurrency: int = 8
                       ) -> Iterator[Dict[str, np.ndarray]]:
    """One epoch through the TASK-BASED loader at the streaming plane's
    exact epoch semantics: the epoch's shard order re-applied to the
    read tasks, real remote read+transform tasks through the object
    store (the windowed task executor), then the SAME shuffle+batch
    stream. This is the measured baseline of the
    ``data_stream_speedup`` probe and the parity reference of the
    streaming tests/chaos soak — same seed => same batches, exactly."""
    import ray_tpu
    from ray_tpu.data._internal import logical as L
    from ray_tpu.data._internal.executor import execute_plan
    from ray_tpu.data.block import block_to_batch

    tasks, fused = split_streamable_plan(ops)
    order = epoch_order(len(tasks), seed, epoch)
    plan: List[Any] = [L.Read(read_tasks=[tasks[int(i)] for i in order],
                              datasource_name="epoch")]
    if fused is not None:
        plan.append(L.OneToOne(fused, label="epoch_map"))

    def np_blocks():
        for ref, _meta in execute_plan(plan, concurrency):
            nb = block_to_batch(ray_tpu.get(ref), "numpy")
            if _np_rows(nb):
                yield nb

    rng = shuffle_rng(seed, epoch) if shuffle_buffer else None
    yield from epoch_batch_stream(
        np_blocks(), batch_size=batch_size, shuffle_buffer=shuffle_buffer,
        rng=rng, drop_last=drop_last)


# ------------------------------------------------------------------ plans


@dataclasses.dataclass
class _ReaderPlan:
    out_spec: _channels.ChannelSpec
    rank: int
    num_readers: int
    num_tasks: int
    seed: Optional[int]
    epochs: int
    send_numpy: bool  # no transform stage: convert blocks reader-side


@dataclasses.dataclass
class _TransformPlan:
    in_spec: _channels.ChannelSpec
    out_spec: _channels.ChannelSpec
    epochs: int


@dataclasses.dataclass
class _BatcherPlan:
    in_specs: List[_channels.ChannelSpec]
    out_spec: _channels.ChannelSpec
    num_tasks: int
    seed: Optional[int]
    epochs: int
    batch_size: int
    shuffle_buffer: Optional[int]
    drop_last: bool


# ------------------------------------------------------- stage actor loops


class _StreamReaderImpl:
    """Shard-reader actor: owns the full read-task list (assignments are
    re-derived per epoch from the seeded order) and streams its shard's
    blocks into one channel — the object store never sees a block."""

    def __init__(self, tasks):
        self._tasks = list(tasks)

    def ping(self) -> str:
        return "ok"

    def probe_sizes(self, transform, batch_size: int,
                    sample: int = 3) -> dict:
        """Packed payload sizes off a few sample tasks so the driver can
        size fixed-shape channels at build — an undersized buffer then
        can only be a loud build/step error, never silent corruption."""
        from ray_tpu.data.block import block_to_batch

        T = len(self._tasks)
        idx = sorted({0, T // 2, T - 1})[:max(1, int(sample))]
        block_b = np_b = row_b = 1
        for i in idx:
            block = self._tasks[i]()
            out = transform(block) if transform is not None else block
            nb = block_to_batch(out, "numpy")
            block_b = max(block_b, len(serialization.pack({"b": block})))
            np_payload = len(serialization.pack({"b": nb}))
            np_b = max(np_b, np_payload)
            row_b = max(row_b, np_payload // max(1, out.num_rows))
        return {"block_bytes": block_b, "np_bytes": np_b,
                "row_bytes": row_b}

    def run_loop(self, plan: _ReaderPlan) -> dict:
        from ray_tpu._private import api, rpc
        from ray_tpu.data.block import block_to_batch

        core = api._core
        if core is None:
            raise RuntimeError("stream reader loop outside a worker")
        open_local, local, release_pins = _channels.open_local_factory(core)
        remote_specs: List[_channels.ChannelSpec] = []
        try:
            out = _channels.VersionedWriter(core, plan.out_spec, open_local)
            if not out.is_local:
                remote_specs.append(plan.out_spec)
        except BaseException:
            release_pins()
            raise

        def close_everything() -> None:
            _channels.close_channels_nowait(
                core, local.values(), remote_specs)

        n = 0  # messages committed (version 2n)
        total = 0
        prev_rpc = rpc._m_client_calls.total()
        try:
            for epoch in range(1, plan.epochs + 1):
                order = epoch_order(plan.num_tasks, plan.seed, epoch)
                mine = order[plan.rank::plan.num_readers]
                blocks = 0
                for t in mine:
                    chaos.maybe_crash("worker.data_stream")
                    t0 = flight.now()
                    block = self._tasks[int(t)]()
                    flight.span_since(_F_READ, t0)
                    payload = serialization.pack(
                        {"b": (block_to_batch(block, "numpy")
                               if plan.send_numpy else block)})
                    n += 1
                    out.write(payload, 2 * n)
                    _m_blocks.inc()
                    blocks += 1
                total += blocks
                now = rpc._m_client_calls.total()
                n += 1
                out.write(serialization.pack({
                    "eof": epoch,
                    "stats": [{"role": "reader", "rank": plan.rank,
                               "epoch": epoch, "blocks": blocks,
                               "rpc_calls": now - prev_rpc}],
                }), 2 * n)
                prev_rpc = now
            return {"blocks": total, "epochs": plan.epochs}
        except ChannelClosedError:
            # teardown (or a peer's death) closed the channels mid-epoch;
            # re-fan the close so every peer unwinds
            try:
                close_everything()
            except Exception:
                logger.exception("reader close-on-exit failed")
            return {"blocks": total, "closed": True}
        except BaseException:
            try:
                close_everything()
            except Exception:
                logger.exception("reader close-on-error failed")
            raise
        finally:
            release_pins()


class _StreamTransformImpl:
    """Transform actor: applies the plan's fused map chain block by
    block (zero-copy views in, one packed write out — inputs acked only
    after the output is committed)."""

    def __init__(self, transform):
        self._transform = transform

    def ping(self) -> str:
        return "ok"

    def run_loop(self, plan: _TransformPlan) -> dict:
        from ray_tpu._private import api, rpc
        from ray_tpu.data.block import block_to_batch

        core = api._core
        if core is None:
            raise RuntimeError("stream transform loop outside a worker")
        open_local, local, release_pins = _channels.open_local_factory(core)
        remote_specs: List[_channels.ChannelSpec] = []
        try:
            in_ch = open_local(plan.in_spec)
            out = _channels.VersionedWriter(core, plan.out_spec, open_local)
            if not out.is_local:
                remote_specs.append(plan.out_spec)
        except BaseException:
            release_pins()
            raise

        def close_everything() -> None:
            _channels.close_channels_nowait(
                core, local.values(), remote_specs)

        n = 0
        blocks = 0
        prev_rpc = rpc._m_client_calls.total()
        epochs_done = 0
        try:
            while True:
                n += 1
                view = in_ch.read(2 * n)
                msg = serialization.unpack(view)
                if "eof" in msg:
                    # eof payloads are in-band (ints/strs) — safe to use
                    # after the ack below
                    epoch = msg["eof"]
                    stats = list(msg["stats"])
                    del msg, view
                    in_ch.ack(0, 2 * n)
                    now = rpc._m_client_calls.total()
                    stats.append({"role": "transform", "epoch": epoch,
                                  "blocks": blocks,
                                  "rpc_calls": now - prev_rpc})
                    prev_rpc = now
                    out.write(serialization.pack(
                        {"eof": epoch, "stats": stats}), 2 * n)
                    blocks = 0
                    epochs_done += 1
                    if epoch >= plan.epochs:
                        return {"epochs": epochs_done}
                    continue
                t0 = flight.now()
                result = self._transform(msg["b"])
                payload = serialization.pack(
                    {"b": block_to_batch(result, "numpy")})
                flight.span_since(_F_TRANSFORM, t0)
                del result, msg, view
                out.write(payload, 2 * n)
                in_ch.ack(0, 2 * n)
                blocks += 1
        except ChannelClosedError:
            try:
                close_everything()
            except Exception:
                logger.exception("transform close-on-exit failed")
            return {"epochs": epochs_done, "closed": True}
        except BaseException:
            try:
                close_everything()
            except Exception:
                logger.exception("transform close-on-error failed")
            raise
        finally:
            release_pins()


class _StreamBatcherImpl:
    """Batcher actor: interleaves its upstreams round-robin (which
    reconstructs the epoch's global shard order exactly), runs the
    shared windowed-shuffle + fixed-shape batch stream, and commits one
    batch per channel write to the consumer."""

    def ping(self) -> str:
        return "ok"

    def run_loop(self, plan: _BatcherPlan) -> dict:
        from ray_tpu._private import api, rpc

        core = api._core
        if core is None:
            raise RuntimeError("stream batcher loop outside a worker")
        open_local, local, release_pins = _channels.open_local_factory(core)
        remote_specs: List[_channels.ChannelSpec] = []
        try:
            in_chs = [open_local(s) for s in plan.in_specs]
            out = _channels.VersionedWriter(core, plan.out_spec, open_local)
            if not out.is_local:
                remote_specs.append(plan.out_spec)
        except BaseException:
            release_pins()
            raise

        def close_everything() -> None:
            _channels.close_channels_nowait(
                core, local.values(), remote_specs)

        R = len(in_chs)
        reads = [0] * R  # per-upstream message count
        m = 0  # downstream messages committed
        total_batches = 0
        prev_rpc = rpc._m_client_calls.total()
        try:
            for epoch in range(1, plan.epochs + 1):
                stage_stats: List[dict] = []
                blocks_in = 0

                def np_blocks():
                    nonlocal blocks_in
                    # block i of the global order came from reader i % R:
                    # round-robin reads reconstruct the order exactly
                    for i in range(plan.num_tasks):
                        chaos.maybe_crash("worker.data_stream")
                        r = i % R
                        reads[r] += 1
                        view = in_chs[r].read(2 * reads[r])
                        msg = serialization.unpack(view)
                        b = _copy_batch(msg["b"])  # one memcpy, then ack
                        del msg, view
                        in_chs[r].ack(0, 2 * reads[r])
                        blocks_in += 1
                        if _np_rows(b):
                            yield b
                    for r in range(R):
                        reads[r] += 1
                        view = in_chs[r].read(2 * reads[r])
                        msg = serialization.unpack(bytes(view))
                        del view
                        in_chs[r].ack(0, 2 * reads[r])
                        stage_stats.extend(msg["stats"])

                rng = (shuffle_rng(plan.seed, epoch)
                       if plan.shuffle_buffer else None)
                batches = 0
                for batch in epoch_batch_stream(
                        np_blocks(), batch_size=plan.batch_size,
                        shuffle_buffer=plan.shuffle_buffer, rng=rng,
                        drop_last=plan.drop_last):
                    t0 = flight.now()
                    m += 1
                    out.write(serialization.pack({"b": batch}), 2 * m)
                    flight.span_since(_F_BATCH, t0)
                    _m_batches.inc()
                    batches += 1
                total_batches += batches
                now = rpc._m_client_calls.total()
                stage_stats.append({"role": "batcher", "epoch": epoch,
                                    "blocks": blocks_in,
                                    "batches": batches,
                                    "rpc_calls": now - prev_rpc})
                prev_rpc = now
                m += 1
                out.write(serialization.pack({
                    "eof": epoch, "batches": batches,
                    "stats": stage_stats}), 2 * m)
            return {"batches": total_batches, "epochs": plan.epochs}
        except ChannelClosedError:
            try:
                close_everything()
            except Exception:
                logger.exception("batcher close-on-exit failed")
            return {"batches": total_batches, "closed": True}
        except BaseException:
            try:
                close_everything()
            except Exception:
                logger.exception("batcher close-on-error failed")
            raise
        finally:
            release_pins()


_reader_cls = _transform_cls = _batcher_cls = None


def _actor_classes():
    global _reader_cls, _transform_cls, _batcher_cls
    if _reader_cls is None:
        import ray_tpu

        _reader_cls = ray_tpu.remote(_StreamReaderImpl)
        _transform_cls = ray_tpu.remote(_StreamTransformImpl)
        _batcher_cls = ray_tpu.remote(_StreamBatcherImpl)
    return _reader_cls, _transform_cls, _batcher_cls


# --------------------------------------------------------------- executor


class StreamingExecutor:
    """Compiled streaming ingest pipeline (module docstring has the
    design)::

        ex = StreamingExecutor(ds._ops, batch_size=256, epochs=3, seed=0)
        for batch in ex.batches():   # numpy dicts, fixed shape
            ...
        ex.shutdown()                # (batches() exhaustion also shuts down)

    ``feed(step)`` hands each batch to a trainer step callable as
    read-only arena views (acked after the step returns) — the
    Data-feeds-Train composition without an extra copy.
    """

    def __init__(self, ops, *, batch_size: int, epochs: int = 1,
                 seed: Optional[int] = 0,
                 shuffle_buffer: Optional[int] = None,
                 num_readers: Optional[int] = None,
                 depth: Optional[int] = None,
                 drop_last: bool = False,
                 buffer_bytes: Optional[int] = None,
                 batch_buffer_bytes: Optional[int] = None,
                 reader_options: Optional[Sequence[dict]] = None,
                 transform_options: Optional[Sequence[dict]] = None,
                 batcher_options: Optional[dict] = None,
                 name: str = "data_stream"):
        import ray_tpu
        from ray_tpu._private import api

        core = api._require_core()
        self._core = core
        if core.arena is None:
            raise RuntimeError(
                "streaming ingest needs a driver attached to a node arena")
        self._batch_size = _require_positive("batch_size", batch_size)
        self._epochs = _require_positive("epochs", epochs)
        self._seed = seed
        if shuffle_buffer is None:
            shuffle_buffer = _default_shuffle(core.config)
        elif int(shuffle_buffer) <= 0:
            raise ValueError(
                f"shuffle_buffer must be positive (got {shuffle_buffer!r}); "
                f"pass None to disable the windowed shuffle")
        self._shuffle = int(shuffle_buffer) if shuffle_buffer else None
        if self._shuffle and seed is None:
            # fail at build on the driver, not inside the batcher actor
            shuffle_rng(seed, 1)
        self._depth = (_require_positive("depth", depth)
                       if depth is not None
                       else _env_stream_depth(core.config))
        self._drop_last = bool(drop_last)
        self._tasks, self._transform = split_streamable_plan(ops)
        T = len(self._tasks)
        R = (min(4, T) if num_readers is None
             else _require_positive("num_readers", num_readers))
        self._R = R = min(R, T)
        self._T = T

        self._dead = False
        self._torn = False
        self._teardown_lock = threading.Lock()
        self._all_specs: List[_channels.ChannelSpec] = []
        self._local_channels: Dict[bytes, _channels.LocalChannel] = {}
        self._loop_refs: List[Any] = []
        self._actor_info: Dict[str, dict] = {}
        self._readers: List[Any] = []
        self._transforms: List[Any] = []
        self._batcher = None
        self._m = 0  # consumer messages read
        self._epoch_stats: List[dict] = []
        self._exhausted = False
        self._consuming = False

        reader_cls, transform_cls, batcher_cls = _actor_classes()

        def options_for(cls, opts, i=None):
            if isinstance(opts, dict):
                o = dict(opts)
            else:
                o = dict(opts[i]) if opts and i is not None \
                    and i < len(opts) and opts[i] else {}
            o.setdefault("num_cpus", 0.5)
            return cls.options(**o)

        # any mid-build failure unwinds through shutdown() — it kills
        # whatever was already created (ActorHandles have no GC-kill)
        try:
            self._readers = [
                options_for(reader_cls, reader_options, r).remote(
                    self._tasks)
                for r in range(R)]
            if self._transform is not None:
                self._transforms = [
                    options_for(transform_cls, transform_options, r).remote(
                        self._transform)
                    for r in range(R)]
            self._batcher = options_for(
                batcher_cls, batcher_options or {}).remote()
            ray_tpu.get([a.ping.remote() for a in self._stage_actors()],
                        timeout=180)
            sizes = ray_tpu.get(self._readers[0].probe_sizes.remote(
                self._transform, self._batch_size), timeout=180)
            # generous slack: block sizes vary across tasks and the probe
            # samples a few — an overflow is a loud write error, and
            # buffer_bytes= overrides when the operator knows better
            self._block_buffer = int(
                buffer_bytes
                or max(sizes["block_bytes"], sizes["np_bytes"]) * 3 // 2
                + 64 * 1024)
            self._batch_buffer = int(
                batch_buffer_bytes
                or sizes["row_bytes"] * self._batch_size * 3 // 2
                + 64 * 1024)
            self._build_channels()
        except BaseException:
            try:
                self.shutdown()
            except Exception:
                logger.debug("streaming build unwind failed", exc_info=True)
            raise
        global _live_executors
        with _live_lock:
            _live_executors += 1
            _m_depth.set(self._depth)
        self._gauge_live = True

    def _stage_actors(self):
        actors = list(self._readers) + list(self._transforms)
        if self._batcher is not None:
            actors.append(self._batcher)
        return actors

    # -- properties the microbenchmark fallback guards key on

    @property
    def is_channel_backed(self) -> bool:
        return bool(self._all_specs) and not self._dead

    @property
    def channel_depth(self) -> int:
        return self._depth

    @property
    def num_readers(self) -> int:
        return self._R

    @property
    def epoch_stats(self) -> List[dict]:
        """Per-epoch reports: batches, consumer stall seconds/fraction,
        the consumer's control-RPC delta, and every stage's in-band
        report (incl. per-epoch ``rpc_calls`` — the zero-RPC proof)."""
        return list(self._epoch_stats)

    # -- build

    def _create_channel(self, node_addr, participants, *,
                        buffer: int) -> _channels.ChannelSpec:
        core = self._core
        spec = _channels.create_channel(
            core, node_addr, buffer, self._depth, 1, participants)
        self._all_specs.append(spec)
        if tuple(node_addr) == tuple(core.supervisor_addr):
            self._local_channels[spec.key()] = _channels.LocalChannel(
                core.arena, spec)
        return spec

    def _build_channels(self) -> None:
        core = self._core
        driver_node = tuple(core.supervisor_addr)
        ctrl = core.clients.get(core.controller_addr)
        views = core._run(ctrl.call("node_views"))
        for a in self._stage_actors():
            hexid = a._actor_id.hex()
            self._actor_info[hexid] = _channels.resolve_actor_placement(
                core, a._actor_id, views)

        # stages are serially dependent through the batcher, so no
        # subset can make progress alone: ANY participant's death closes
        # every channel of the pipeline
        participants = {core._store_client_id}
        for info in self._actor_info.values():
            participants.add(info["worker_id_hex"])
            participants.add(f"node:{info['node_id_hex']}")

        def node_of(actor):
            return self._actor_info[actor._actor_id.hex()]["node_addr"]

        has_t = bool(self._transforms)
        mid_consumers = self._transforms if has_t else [self._batcher] * \
            self._R
        # every channel lives on its READER's node: same-node writers hit
        # the seqlock directly, cross-node writers push chunked mirrors
        reader_out = [self._create_channel(
            node_of(mid_consumers[r]), participants,
            buffer=self._block_buffer) for r in range(self._R)]
        if has_t:
            transform_out = [self._create_channel(
                node_of(self._batcher), participants,
                buffer=self._block_buffer) for _ in range(self._R)]
            batcher_in = transform_out
        else:
            batcher_in = reader_out
        self._out_spec = self._create_channel(
            driver_node, participants, buffer=self._batch_buffer)
        self._out_ch = self._local_channels[self._out_spec.key()]

        for hexid in self._actor_info:
            core.subscribe("actor:" + hexid, self._on_actor_update)

        for r, actor in enumerate(self._readers):
            self._loop_refs.append(actor.run_loop.remote(_ReaderPlan(
                out_spec=reader_out[r], rank=r, num_readers=self._R,
                num_tasks=self._T, seed=self._seed, epochs=self._epochs,
                send_numpy=not has_t)))
        if has_t:
            for r, actor in enumerate(self._transforms):
                self._loop_refs.append(actor.run_loop.remote(
                    _TransformPlan(in_spec=reader_out[r],
                                   out_spec=transform_out[r],
                                   epochs=self._epochs)))
        self._loop_refs.append(self._batcher.run_loop.remote(_BatcherPlan(
            in_specs=batcher_in, out_spec=self._out_spec,
            num_tasks=self._T, seed=self._seed, epochs=self._epochs,
            batch_size=self._batch_size, shuffle_buffer=self._shuffle,
            drop_last=self._drop_last)))

    # -- failure fan-out (the pipeline trainer's shape)

    def _on_actor_update(self, message) -> None:
        if self._dead or not isinstance(message, dict):
            return
        if message.get("state") in ("DEAD", "RESTARTING"):
            self._close_for_failure()

    def _close_for_failure(self) -> None:
        self._dead = True
        _channels.close_channels_nowait(
            self._core, self._local_channels.values(), self._all_specs)

    def _surface_failure(self, closed: ChannelClosedError):
        self._close_for_failure()
        _channels.surface_loop_failure(self._core, self._loop_refs, closed)

    # -- consumption

    def batches(self, copy: bool = True) -> Iterator[Dict[str, np.ndarray]]:
        """The consumer stream: one channel read per fixed-shape batch.

        ``copy=False`` yields READ-ONLY views over the driver's arena
        mmap (zero-copy); each view is valid until the next ``next()``
        — the ack that frees the batcher's slot is deferred until the
        consumer asks for more, which is what ``feed`` relies on to
        hand batches to a trainer without a copy. A mid-epoch
        participant death raises the loop's real error (never a
        silently truncated epoch)."""
        if self._dead:
            raise ChannelClosedError("streaming executor was torn down")
        if self._exhausted:
            raise RuntimeError(
                "streaming executor already consumed; build a new one "
                "(epochs are fixed at build time)")
        if self._consuming:
            # two live iterators would interleave reads of the one
            # consumer channel through the shared message counter —
            # each seeing a disjoint subset of batches, silently
            raise RuntimeError(
                "another batches() iterator is already consuming this "
                "executor")
        self._consuming = True
        try:
            yield from self._batches(copy)
        finally:
            self._consuming = False

    def _batches(self, copy: bool) -> Iterator[Dict[str, np.ndarray]]:
        from ray_tpu._private import rpc

        epoch_t0 = None
        stall_s = 0.0
        batches = 0
        prev_rpc = rpc._m_client_calls.total()
        while True:
            v = 2 * (self._m + 1)
            t0 = time.perf_counter()
            try:
                view = self._out_ch.read(v)
            except ChannelClosedError as e:
                self._surface_failure(e)
            wait = time.perf_counter() - t0
            self._m += 1
            if epoch_t0 is None:
                # the wait for an epoch's first batch spans pipeline
                # spin-up and the driver's think-time — start the epoch
                # clock here; later waits are genuine input stalls
                epoch_t0 = time.perf_counter()
            else:
                stall_s += wait
                _m_stall.inc(wait)
                flight.instant(_F_STALL, int(wait * 1e6))
            msg = serialization.unpack(view)
            if "eof" in msg:
                epoch = msg["eof"]
                stats = list(msg["stats"])
                del msg, view
                self._out_ch.ack(0, v)
                now = rpc._m_client_calls.total()
                wall = max(time.perf_counter() - epoch_t0, 1e-9)
                self._epoch_stats.append({
                    "epoch": epoch, "batches": batches,
                    "stall_s": stall_s,
                    "stall_fraction": min(1.0, stall_s / wall),
                    "consumer_rpc_calls": now - prev_rpc,
                    "stage_reports": stats,
                })
                prev_rpc = now
                epoch_t0, stall_s, batches = None, 0.0, 0
                if epoch >= self._epochs:
                    self._exhausted = True
                    return
                continue
            batches += 1
            if copy:
                b = _copy_batch(msg["b"])
                del msg, view
                self._out_ch.ack(0, v)
                yield b
            else:
                try:
                    yield msg["b"]
                finally:
                    del msg, view
                    self._out_ch.ack(0, v)

    def feed(self, step: Callable[[Dict[str, np.ndarray]], Any]
             ) -> Iterator[Any]:
        """Hand every batch straight to a trainer step (e.g.
        ``PipelineTrainer.step`` or a Sebulba learner update) as
        read-only arena views — the batch never leaves the arena; the
        channel slot is acked after the step returns. Yields each
        step's result."""
        for batch in self.batches(copy=False):
            yield step(batch)

    # -- teardown

    def shutdown(self, kill_actors: bool = True,
                 timeout: float = 30) -> Dict[str, Any]:
        """Close every channel, drain the stage loops, release the pins,
        (optionally) kill the stage actors. Idempotent."""
        self._dead = True
        with self._teardown_lock:
            if self._torn:
                return {}
            self._torn = True
        if getattr(self, "_gauge_live", False):
            global _live_executors
            with _live_lock:
                _live_executors -= 1
                if _live_executors <= 0:
                    _m_depth.set(0)
        core = self._core
        for ch in self._local_channels.values():
            try:
                ch.close()
            except Exception:
                pass
        for hexid in self._actor_info:
            try:
                core.unsubscribe("actor:" + hexid, self._on_actor_update)
            except Exception:
                pass
        _channels.close_specs(core, self._all_specs)
        stats: Dict[str, Any] = {"loops": []}
        for ref in self._loop_refs:
            try:
                stats["loops"].append(core.get([ref], timeout=timeout)[0])
            except Exception:
                stats["loops"].append(None)
        _channels.free_and_unpin_specs(core, self._all_specs)
        if kill_actors:
            import ray_tpu

            for a in self._stage_actors():
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass
        return stats

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


class StreamingBatches:
    """The iterator `Dataset.stream_batches` returns: owns a
    StreamingExecutor, yields its batches, and shuts it down on
    exhaustion or early close (a `break` releases the actors/pins)."""

    def __init__(self, ops, **kw):
        self.executor = StreamingExecutor(ops, **kw)

    @property
    def epoch_stats(self) -> List[dict]:
        return self.executor.epoch_stats

    def __iter__(self):
        try:
            yield from self.executor.batches()
        finally:
            self.executor.shutdown()
