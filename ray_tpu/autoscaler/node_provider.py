"""Node providers: how the autoscaler actually gets machines.

Analog of `python/ray/autoscaler/node_provider.py` (the plugin interface)
and `python/ray/autoscaler/_private/gcp/` (the GCP implementation whose
TPU handling lives at `gcp/config.py:16-57`). Two implementations here:

  * LocalNodeProvider — spawns real supervisor processes on this host;
    the hermetic test/provider used by the autoscaler tests (reference
    analog: the fake multi-node provider in `_private/fake_multi_node`).
  * GCPTPUNodeProvider — maps TPU slice topologies to node shapes and
    would drive the GCE/TPU API; the API calls are isolated behind
    `_api_create/_api_terminate` so the shape logic is testable offline.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class NodeType:
    """One launchable host shape (≈ available_node_types entries in the
    reference's autoscaler YAML)."""

    name: str
    resources: Dict[str, float]
    max_workers: int = 10
    # provider-specific payload (e.g. GCE machine type / TPU topology)
    node_config: Dict[str, Any] = dataclasses.field(default_factory=dict)


class NodeProvider:
    """Minimal provider surface the autoscaler drives.

    Contract for v2 reconciliation: a provider must arrange for each
    launched node's supervisor to advertise the node label
    ``provider_id=<its provider node id>`` — that label is the join key
    between the cloud view and the control-plane view
    (`autoscaler/v2.py` ``Reconciler._sync_cluster``)."""

    def create_node(self, node_type: NodeType, count: int) -> List[str]:
        """Launch `count` nodes of the type; returns provider node ids."""
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[Dict[str, Any]]:
        """[{id, node_type, node_id_hex?}] for nodes this provider launched
        and has not terminated."""
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Spawns supervisor processes on this host (one per 'node').

    Wraps a `ray_tpu.cluster_utils.Cluster`-compatible session: it talks
    to the same controller and session dir, so autoscaled nodes join the
    cluster exactly like `Cluster.add_node` ones.
    """

    def __init__(self, session_dir: str, controller_addr, config=None):
        from ray_tpu._private.config import Config

        self._session_dir = session_dir
        self._controller_addr = controller_addr
        self._config = config or Config.from_env()
        self._lock = threading.Lock()
        self._nodes: Dict[str, Dict[str, Any]] = {}
        self._next_id = 0

    def create_node(self, node_type: NodeType, count: int) -> List[str]:
        from ray_tpu._private.node import start_supervisor

        out = []
        resources = {k: float(v) for k, v in node_type.resources.items()}
        resources.setdefault("memory", 2.0 * 1024**3)
        with self._lock:
            for _ in range(count):
                self._next_id += 1
                pid = f"local-{node_type.name}-{self._next_id}"
                proc, addr = start_supervisor(
                    self._session_dir,
                    self._config,
                    self._controller_addr,
                    resources=dict(resources),
                    node_name=pid,
                    # the provider<->control-plane join key the v2
                    # reconciler matches on (v2.py _sync_cluster); every
                    # NodeProvider must arrange for the node's supervisor
                    # to advertise it
                    labels={"provider_id": pid,
                            "node_type": node_type.name},
                )
                self._nodes[pid] = {
                    "id": pid,
                    "node_type": node_type.name,
                    "proc": proc,
                    "address": addr,
                }
                out.append(pid)
        return out

    def terminate_node(self, provider_node_id: str) -> None:
        with self._lock:
            rec = self._nodes.pop(provider_node_id, None)
        if rec is not None:
            try:
                rec["proc"].kill()
                rec["proc"].wait(timeout=5)
            except Exception:
                pass

    def non_terminated_nodes(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                {"id": r["id"], "node_type": r["node_type"],
                 "node_name": r["id"]}
                for r in self._nodes.values()
            ]

    def shutdown(self) -> None:
        for pid in [r["id"] for r in self.non_terminated_nodes()]:
            self.terminate_node(pid)


# TPU slice shapes: topology -> (hosts, chips per host). The head
# resource marks host 0 of a slice so gang placement can pin the
# coordinator (ray_tpu.parallel.slices convention).
TPU_TOPOLOGIES: Dict[str, Dict[str, int]] = {
    "v4-8": {"hosts": 1, "chips_per_host": 4},
    "v4-16": {"hosts": 2, "chips_per_host": 4},
    "v5p-8": {"hosts": 1, "chips_per_host": 4},
    "v5p-16": {"hosts": 2, "chips_per_host": 4},
    "v5p-64": {"hosts": 8, "chips_per_host": 4},
    "v5e-4": {"hosts": 1, "chips_per_host": 4},
    "v5e-8": {"hosts": 1, "chips_per_host": 8},
    "v5e-16": {"hosts": 2, "chips_per_host": 8},
    "v6e-8": {"hosts": 1, "chips_per_host": 8},
}


def tpu_slice_node_types(topology: str, *, cpus_per_host: float = 120.0,
                         max_slices: int = 4) -> List[NodeType]:
    """Expand a TPU slice topology into launchable host node-types
    (≈ the reference's GCP TPU config handling, gcp/config.py:16-57:
    one replicated worker pool per slice, `TPU` chips as resources)."""
    if topology not in TPU_TOPOLOGIES:
        raise ValueError(
            f"unknown TPU topology {topology!r}; known: "
            f"{sorted(TPU_TOPOLOGIES)}")
    shape = TPU_TOPOLOGIES[topology]
    accel = topology.split("-")[0]
    types = [
        NodeType(
            name=f"tpu-{topology}-host",
            resources={
                "CPU": cpus_per_host,
                "TPU": float(shape["chips_per_host"]),
                f"accelerator_type:{accel.upper()}": 1.0,
            },
            max_workers=shape["hosts"] * max_slices,
            node_config={"topology": topology,
                         "hosts_per_slice": shape["hosts"]},
        )
    ]
    return types


class GCPTPUNodeProvider(NodeProvider):
    """GCE/TPU provider skeleton: full shape mapping, stubbed API calls.

    The control flow and node bookkeeping are real; `_api_create` /
    `_api_terminate` raise unless a transport is injected (this image has
    no network egress). Reference: `python/ray/autoscaler/_private/gcp/
    node_provider.py` + TPU pod handling in `gcp/config.py:16-57`.
    """

    def __init__(self, project: str, zone: str,
                 api_client: Optional[Any] = None):
        self.project = project
        self.zone = zone
        self._api = api_client
        self._nodes: Dict[str, Dict[str, Any]] = {}
        self._next = 0

    def create_node(self, node_type: NodeType, count: int) -> List[str]:
        out = []
        for _ in range(count):
            self._next += 1
            name = f"tpu-{node_type.node_config.get('topology', 'host')}-{self._next}"
            self._api_create(name, node_type)
            self._nodes[name] = {"id": name, "node_type": node_type.name}
            out.append(name)
        return out

    def terminate_node(self, provider_node_id: str) -> None:
        if provider_node_id in self._nodes:
            self._api_terminate(provider_node_id)
            self._nodes.pop(provider_node_id, None)

    def non_terminated_nodes(self) -> List[Dict[str, Any]]:
        return [dict(r) for r in self._nodes.values()]

    # -- API boundary (injectable for tests; raises without a client) --

    def _api_create(self, name: str, node_type: NodeType) -> None:
        if self._api is None:
            raise RuntimeError(
                "GCPTPUNodeProvider needs an api_client (no network egress "
                "in this environment); inject one or use LocalNodeProvider")
        self._api.create(
            project=self.project, zone=self.zone, name=name,
            accelerator_type=node_type.node_config.get("topology"),
            resources=node_type.resources)

    def _api_terminate(self, name: str) -> None:
        if self._api is None:
            raise RuntimeError("GCPTPUNodeProvider needs an api_client")
        self._api.terminate(project=self.project, zone=self.zone, name=name)
