"""StandardAutoscaler — the demand → node-launch reconciler.

Analog of `python/ray/autoscaler/_private/autoscaler.py:172`
(StandardAutoscaler.update) + `resource_demand_scheduler.py` (bin-packing
pending demand into node launches): each `update()`

  1. reads cluster state from the controller (`autoscaler_state` RPC:
     node views + the pending-lease demand every supervisor gossips),
  2. simulates placing the pending demand onto current capacity,
  3. bin-packs the unmet remainder into the cheapest feasible node types
     and launches them through the NodeProvider,
  4. terminates nodes idle longer than `idle_timeout_s` (never below
     `min_workers`, never the head node).

Run it from any process that can reach the controller — typically the
head (`autoscaler.run_in_thread()`), mirroring the reference's monitor
process driving StandardAutoscaler.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.autoscaler.node_provider import NodeProvider, NodeType

logger = logging.getLogger(__name__)

Address = Tuple[str, int]


@dataclasses.dataclass
class AutoscalerConfig:
    node_types: List[NodeType] = dataclasses.field(default_factory=list)
    max_workers: int = 8          # autoscaled nodes, cluster-wide
    min_workers: int = 0
    idle_timeout_s: float = 60.0
    update_interval_s: float = 2.0
    # a launch is assumed in flight this long; suppresses double-launch
    # while the new supervisor registers
    launch_grace_s: float = 30.0


class StandardAutoscaler:
    def __init__(self, controller_addr: Address, provider: NodeProvider,
                 config: AutoscalerConfig):
        self.controller_addr = tuple(controller_addr)
        self.provider = provider
        self.config = config
        self._launches: List[Tuple[float, str]] = []  # (ts, node_type)
        self._seen_nodes: set = set()  # provider node_names seen alive
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    # ------------------------------------------------------------- state

    def _fetch_state(self) -> dict:
        import asyncio

        from ray_tpu._private.rpc import RpcClient

        async def go():
            client = RpcClient(self.controller_addr)
            try:
                return await client.call("autoscaler_state", timeout=10)
            finally:
                await client.close()

        return asyncio.run(go())

    # ------------------------------------------------------------ update

    def update(self) -> Dict[str, Any]:
        """One reconcile pass; returns a summary for logging/tests."""
        state = self._fetch_state()
        alive = [n for n in state["nodes"] if n["alive"]]
        self._prune_registered_launches(alive)
        demand: List[Dict[str, float]] = []
        for n in alive:
            demand.extend(n.get("pending_demand", []))

        unmet = _unmet_after_packing(demand, alive, self._pending_types())
        to_launch = _nodes_to_launch(
            unmet, self.config.node_types,
            current=self._autoscaled_count(alive),
            max_workers=self.config.max_workers,
            existing_by_type=self._alive_counts_by_type(alive))
        for node_type, count in to_launch.items():
            nt = next(t for t in self.config.node_types
                      if t.name == node_type)
            logger.info("autoscaler launching %d x %s", count, nt.name)
            self.provider.create_node(nt, count)
            now = time.monotonic()
            self._launches.extend((now, nt.name) for _ in range(count))

        removed = self._scale_down_idle(alive, demand)
        return {"demand": len(demand), "unmet": len(unmet),
                "launched": dict(to_launch), "removed": removed}

    def _provider_types_by_name(self) -> Dict[str, str]:
        return {n.get("node_name", n["id"]): n["node_type"]
                for n in self.provider.non_terminated_nodes()}

    def _prune_registered_launches(self, alive) -> None:
        """A launch that has registered must stop counting as in-flight —
        otherwise it is double-counted (live capacity AND pending pool),
        suppressing legitimate scale-up until the grace window lapses."""
        types_by_name = self._provider_types_by_name()
        for n in alive:
            name = n.get("labels", {}).get("node_name")
            if name in types_by_name and name not in self._seen_nodes:
                self._seen_nodes.add(name)
                ntype = types_by_name[name]
                for i, (_ts, lt) in enumerate(self._launches):
                    if lt == ntype:
                        self._launches.pop(i)
                        break

    def _alive_counts_by_type(self, alive) -> Dict[str, int]:
        """Existing autoscaled nodes per type (+ in-flight launches), for
        per-type max_workers enforcement across update() calls."""
        types_by_name = self._provider_types_by_name()
        counts: Dict[str, int] = {}
        for n in alive:
            ntype = types_by_name.get(n.get("labels", {}).get("node_name"))
            if ntype is not None:
                counts[ntype] = counts.get(ntype, 0) + 1
        for _ts, ntype in self._launches:
            counts[ntype] = counts.get(ntype, 0) + 1
        return counts

    def _pending_types(self) -> List[NodeType]:
        """Launches still in their grace window count as capacity so a
        slow-to-register node isn't launched twice."""
        now = time.monotonic()
        self._launches = [
            (ts, name) for ts, name in self._launches
            if now - ts < self.config.launch_grace_s
        ]
        by_name = {t.name: t for t in self.config.node_types}
        return [by_name[name] for _, name in self._launches
                if name in by_name]

    def _autoscaled_count(self, alive) -> int:
        provider_names = {n.get("node_name", n["id"])
                          for n in self.provider.non_terminated_nodes()}
        return sum(
            1 for n in alive
            if n.get("labels", {}).get("node_name") in provider_names
        ) + len(self._launches)

    def _scale_down_idle(self, alive, demand) -> List[str]:
        if demand:
            return []  # never shrink under pending demand
        removed = []
        provider_nodes = {n.get("node_name", n["id"]): n["id"]
                          for n in self.provider.non_terminated_nodes()}
        autoscaled_alive = [
            n for n in alive
            if n.get("labels", {}).get("node_name") in provider_nodes
        ]
        keep = max(self.config.min_workers, 0)
        for n in autoscaled_alive:
            if len(autoscaled_alive) - len(removed) <= keep:
                break
            if n["idle_s"] > self.config.idle_timeout_s and \
                    dict(n["available"]) == dict(n["total"]):
                pid = provider_nodes[n["labels"]["node_name"]]
                logger.info("autoscaler terminating idle node %s", pid)
                self.provider.terminate_node(pid)
                removed.append(pid)
        return removed

    # ------------------------------------------------------------- loop

    def run_in_thread(self) -> threading.Thread:
        def loop():
            while not self._stopped.wait(self.config.update_interval_s):
                try:
                    self.update()
                except Exception:
                    logger.exception("autoscaler update failed")

        self._thread = threading.Thread(
            target=loop, name="autoscaler", daemon=True)
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        self._stopped.set()


# ---------------------------------------------------------------- packing


def _unmet_after_packing(demand: List[Dict[str, float]], alive,
                         pending_types: List[NodeType]) -> List[Dict[str, float]]:
    """Simulate placing each demand bundle on current + in-flight
    capacity; return the bundles that do not fit anywhere
    (≈ get_bin_pack_residual, resource_demand_scheduler.py)."""
    pools: List[Dict[str, float]] = [dict(n["available"]) for n in alive]
    pools.extend(dict(t.resources) for t in pending_types)
    unmet: List[Dict[str, float]] = []
    for bundle in demand:
        placed = False
        for pool in pools:
            if all(pool.get(k, 0.0) >= v for k, v in bundle.items()):
                for k, v in bundle.items():
                    pool[k] = pool.get(k, 0.0) - v
                placed = True
                break
        if not placed:
            unmet.append(bundle)
    return unmet


def _nodes_to_launch(unmet: List[Dict[str, float]],
                     node_types: List[NodeType], *, current: int,
                     max_workers: int,
                     existing_by_type: Optional[Dict[str, int]] = None,
                     ) -> Dict[str, int]:
    """Bin-pack unmet bundles into the fewest new nodes, smallest
    feasible type first (utilization-based scoring simplified to
    resource-sum ordering). Per-type max_workers counts nodes that
    already exist (existing_by_type), not just this pass's launches."""
    launches: Dict[str, int] = {}
    existing_by_type = existing_by_type or {}
    budget = max(0, max_workers - current)
    if not budget:
        return launches
    ordered = sorted(node_types,
                     key=lambda t: sum(t.resources.values()))
    open_pools: List[Tuple[str, Dict[str, float]]] = []
    for bundle in unmet:
        placed = False
        for name, pool in open_pools:
            if all(pool.get(k, 0.0) >= v for k, v in bundle.items()):
                for k, v in bundle.items():
                    pool[k] = pool.get(k, 0.0) - v
                placed = True
                break
        if placed:
            continue
        for t in ordered:
            fits = all(t.resources.get(k, 0.0) >= v
                       for k, v in bundle.items())
            within = (launches.get(t.name, 0)
                      + existing_by_type.get(t.name, 0)) < t.max_workers
            if fits and within and sum(launches.values()) < budget:
                pool = dict(t.resources)
                for k, v in bundle.items():
                    pool[k] = pool.get(k, 0.0) - v
                open_pools.append((t.name, pool))
                launches[t.name] = launches.get(t.name, 0) + 1
                break
        # an unfittable bundle (no type big enough) is simply skipped —
        # it stays parked in the supervisor's infeasible queue
    return launches
