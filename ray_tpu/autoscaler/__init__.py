"""ray_tpu.autoscaler — demand-driven cluster scaling.

Analog of the reference autoscaler
(`python/ray/autoscaler/_private/autoscaler.py:172` StandardAutoscaler,
`resource_demand_scheduler.py` bin-packing, `node_provider.py` plugin
interface), TPU-reshaped: node types are host shapes (a TPU slice host is
one node type with its chip count as a resource), and the demand signal
is the pending-lease gossip every supervisor already syncs to the
controller.
"""

from ray_tpu.autoscaler.autoscaler import AutoscalerConfig, StandardAutoscaler
from ray_tpu.autoscaler.node_provider import (GCPTPUNodeProvider,
                                              LocalNodeProvider, NodeProvider,
                                              NodeType)

__all__ = [
    "AutoscalerConfig",
    "StandardAutoscaler",
    "NodeProvider",
    "NodeType",
    "LocalNodeProvider",
    "GCPTPUNodeProvider",
]
