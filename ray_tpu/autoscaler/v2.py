"""Autoscaler v2: instance manager + declarative reconciler.

Analog of the reference's autoscaler rearchitecture
(`python/ray/autoscaler/v2/autoscaler.py`,
`v2/instance_manager/instance_manager.py`,
`v2/instance_manager/reconciler.py`): instead of v1's imperative
launch-and-forget loop, every node the autoscaler touches is an
**Instance** with an explicit lifecycle

    QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING
                  |            |            |
                  v            v            v
        ALLOCATION_FAILED   TERMINATING -> TERMINATED

recorded with a status history, and one idempotent ``reconcile()`` pass
per tick diffs desired against observed state from BOTH sources of
truth (the cloud provider's live node list and the control plane's node
table), issuing only the deltas. Crash-restart safe: every decision is
re-derivable from (instances, provider view, cluster view) — nothing
depends on remembering a previous pass. Reuses v1's bin-packing
(`_unmet_after_packing` / `_nodes_to_launch`) for the sizing decision;
what v2 rearchitects is everything around it.
"""

from __future__ import annotations

import dataclasses
import logging
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.autoscaler import (AutoscalerConfig,
                                           _nodes_to_launch,
                                           _unmet_after_packing)
from ray_tpu.autoscaler.node_provider import NodeProvider, NodeType

logger = logging.getLogger(__name__)

# instance lifecycle states (≈ v2/schema Instance.status values)
QUEUED = "QUEUED"
REQUESTED = "REQUESTED"
ALLOCATED = "ALLOCATED"
RAY_RUNNING = "RAY_RUNNING"
ALLOCATION_FAILED = "ALLOCATION_FAILED"
TERMINATING = "TERMINATING"
TERMINATED = "TERMINATED"

_VALID_TRANSITIONS = {
    QUEUED: {REQUESTED, TERMINATED},
    REQUESTED: {ALLOCATED, ALLOCATION_FAILED},
    ALLOCATED: {RAY_RUNNING, TERMINATING},
    RAY_RUNNING: {TERMINATING},
    ALLOCATION_FAILED: {QUEUED, TERMINATED},
    TERMINATING: {TERMINATED},
    TERMINATED: set(),
}


@dataclasses.dataclass
class Instance:
    instance_id: str
    node_type: str
    status: str = QUEUED
    provider_id: str = ""       # cloud node id once ALLOCATED
    node_id_hex: str = ""       # control-plane node id once RAY_RUNNING
    launch_request_id: str = ""
    retries: int = 0
    updated_at: float = 0.0
    history: List[Any] = dataclasses.field(default_factory=list)


class InstanceManager:
    """Versioned instance table with validated transitions
    (≈ v2/instance_manager/instance_manager.py)."""

    def __init__(self):
        self.instances: Dict[str, Instance] = {}
        self.version = 0

    def create(self, node_type: str, request_id: str) -> Instance:
        inst = Instance(instance_id=uuid.uuid4().hex[:12],
                        node_type=node_type,
                        launch_request_id=request_id,
                        updated_at=time.monotonic())
        inst.history.append((inst.updated_at, QUEUED, "created"))
        self.instances[inst.instance_id] = inst
        self.version += 1
        return inst

    def transition(self, inst: Instance, status: str, reason: str = ""):
        if status not in _VALID_TRANSITIONS[inst.status]:
            raise ValueError(
                f"invalid transition {inst.status} -> {status} "
                f"for {inst.instance_id}")
        inst.status = status
        inst.updated_at = time.monotonic()
        inst.history.append((inst.updated_at, status, reason))
        self.version += 1

    def by_status(self, *statuses: str) -> List[Instance]:
        return [i for i in self.instances.values() if i.status in statuses]

    def gc_terminated(self, keep_s: float = 600.0) -> None:
        cut = time.monotonic() - keep_s
        for iid in [i.instance_id for i in self.instances.values()
                    if i.status == TERMINATED and i.updated_at < cut]:
            del self.instances[iid]


class Reconciler:
    """One idempotent pass: observe, diff, act
    (≈ v2/instance_manager/reconciler.py Reconciler.reconcile)."""

    ALLOCATION_TIMEOUT_S = 120.0
    MAX_ALLOCATION_RETRIES = 3

    def __init__(self, config: AutoscalerConfig, provider: NodeProvider,
                 im: Optional[InstanceManager] = None,
                 idle_timeout_s: float = 60.0):
        self.config = config
        self.provider = provider
        self.im = im or InstanceManager()
        self.idle_timeout_s = idle_timeout_s
        self._idle_since: Dict[str, float] = {}
        # (node_type, expires_at) markers for timed-out allocation
        # requests that may still fill late; consumed by terminating the
        # stray node, and EXPIRED after 2x the allocation timeout so a
        # never-filled stockout can't leave a permanent kill-marker that
        # would reap a legitimate out-of-band node months later
        self._abandoned_requests: List[Tuple[str, float]] = []

    # ---- observation sync ------------------------------------------

    def _sync_provider(self) -> None:
        """Match REQUESTED/ALLOCATED instances against the provider's
        live node list; time out requests the cloud never filled."""
        live = {n["id"]: n for n in self.provider.non_terminated_nodes()}
        claimed = {i.provider_id for i in self.im.instances.values()
                   if i.provider_id}
        for inst in self.im.by_status(REQUESTED):
            # adopt an unclaimed provider node of the right type
            match = next(
                (pid for pid, n in live.items()
                 if n["node_type"] == inst.node_type
                 and pid not in claimed), None)
            if match is not None:
                inst.provider_id = match
                claimed.add(match)
                self.im.transition(inst, ALLOCATED, f"provider {match}")
            elif (time.monotonic() - inst.updated_at
                  > self.ALLOCATION_TIMEOUT_S):
                self.im.transition(inst, ALLOCATION_FAILED,
                                   "allocation timed out (stockout?)")
                # the cloud request is still outstanding: if it fills
                # AFTER the retry's request, the stray node must be
                # terminated, not silently leaked as a billable orphan
                self._abandoned_requests.append(
                    (inst.node_type, time.monotonic()
                     + max(2 * self.ALLOCATION_TIMEOUT_S, 300.0)))
        # reap late fills of abandoned requests: a live provider node no
        # instance claims, of an abandoned type, is terminated (consume
        # one marker per node so legitimate future launches still adopt)
        now = time.monotonic()
        self._abandoned_requests = [
            m for m in self._abandoned_requests if m[1] > now]
        if self._abandoned_requests:
            claimed = {i.provider_id for i in self.im.instances.values()
                       if i.provider_id}
            for pid, n in list(live.items()):
                if pid in claimed:
                    continue
                marker = next((m for m in self._abandoned_requests
                               if m[0] == n["node_type"]), None)
                if marker is not None and not any(
                        i.status == REQUESTED
                        and i.node_type == n["node_type"]
                        for i in self.im.instances.values()):
                    self._abandoned_requests.remove(marker)
                    self.provider.terminate_node(pid)
                    live.pop(pid, None)
        for inst in self.im.by_status(ALLOCATED, RAY_RUNNING):
            if inst.provider_id not in live:
                # the cloud reclaimed it under us (preemption)
                self.im.transition(inst, TERMINATING,
                                   "provider node disappeared")
                self.im.transition(inst, TERMINATED, "gone")

    def _sync_cluster(self, alive_nodes: List[dict]) -> None:
        """Match ALLOCATED instances against registered control-plane
        nodes; detect RAY_RUNNING instances whose node died."""
        by_provider = {}
        for n in alive_nodes:
            pid = n.get("labels", {}).get("provider_id", "")
            if pid:
                by_provider[pid] = n
        for inst in self.im.by_status(ALLOCATED):
            node = by_provider.get(inst.provider_id)
            if node is not None:
                inst.node_id_hex = node["node_id_hex"]
                self.im.transition(inst, RAY_RUNNING,
                                   f"node {inst.node_id_hex[:8]}")
        for inst in self.im.by_status(RAY_RUNNING):
            if inst.provider_id not in by_provider:
                self.im.transition(inst, TERMINATING, "node died")
                self.provider.terminate_node(inst.provider_id)
                self.im.transition(inst, TERMINATED, "terminated")

    # ---- actuation --------------------------------------------------

    def _retry_failed(self) -> None:
        for inst in self.im.by_status(ALLOCATION_FAILED):
            if inst.retries < self.MAX_ALLOCATION_RETRIES:
                inst.retries += 1
                self.im.transition(inst, QUEUED,
                                   f"retry {inst.retries}")
            else:
                self.im.transition(inst, TERMINATED, "retries exhausted")

    def _launch_queued(self) -> None:
        by_type: Dict[str, List[Instance]] = {}
        for inst in self.im.by_status(QUEUED):
            by_type.setdefault(inst.node_type, []).append(inst)
        for type_name, insts in by_type.items():
            nt = next(t for t in self.config.node_types
                      if t.name == type_name)
            try:
                self.provider.create_node(nt, len(insts))
            except Exception as e:
                for inst in insts:
                    self.im.transition(inst, REQUESTED, "create_node")
                    self.im.transition(inst, ALLOCATION_FAILED, str(e))
                continue
            for inst in insts:
                self.im.transition(inst, REQUESTED, "create_node")

    def _desired_new(self, alive: List[dict],
                     demand: List[Dict[str, float]]) -> Dict[str, int]:
        pending = [i for i in self.im.by_status(QUEUED, REQUESTED,
                                                ALLOCATED)]
        pending_types = []
        for i in pending:
            nt = next((t for t in self.config.node_types
                       if t.name == i.node_type), None)
            if nt is not None:
                pending_types.append(nt)
        unmet = _unmet_after_packing(demand, alive, pending_types)
        existing: Dict[str, int] = {}
        for i in self.im.by_status(REQUESTED, ALLOCATED, RAY_RUNNING):
            existing[i.node_type] = existing.get(i.node_type, 0) + 1
        current = len(pending) + len(self.im.by_status(RAY_RUNNING))
        return _nodes_to_launch(unmet, self.config.node_types,
                                current=current,
                                max_workers=self.config.max_workers,
                                existing_by_type=existing)

    def _scale_down_idle(self, alive: List[dict],
                         demand: List[Dict[str, float]]) -> List[str]:
        removed = []
        now = time.monotonic()
        busy_ok = not demand
        by_node = {i.node_id_hex: i
                   for i in self.im.by_status(RAY_RUNNING)}
        for n in alive:
            inst = by_node.get(n["node_id_hex"])
            if inst is None:
                continue
            idle = n["available"] == n["total"]
            if not (idle and busy_ok):
                self._idle_since.pop(inst.instance_id, None)
                continue
            since = self._idle_since.setdefault(inst.instance_id, now)
            if now - since >= self.idle_timeout_s:
                self.im.transition(inst, TERMINATING, "idle timeout")
                self.provider.terminate_node(inst.provider_id)
                self.im.transition(inst, TERMINATED, "terminated")
                removed.append(inst.instance_id)
        return removed

    # ---- the pass ---------------------------------------------------

    def reconcile(self, cluster_state: dict) -> Dict[str, Any]:
        alive = [n for n in cluster_state["nodes"] if n["alive"]]
        demand: List[Dict[str, float]] = []
        for n in alive:
            demand.extend(n.get("pending_demand", []))

        self._sync_provider()
        self._sync_cluster(alive)
        self._retry_failed()

        request_id = uuid.uuid4().hex[:8]
        to_launch = self._desired_new(alive, demand)
        for type_name, count in to_launch.items():
            for _ in range(count):
                self.im.create(type_name, request_id)
        self._launch_queued()
        removed = self._scale_down_idle(alive, demand)
        self.im.gc_terminated()
        return {
            "demand": len(demand),
            "launching": dict(to_launch),
            "removed": removed,
            "instances": {
                s: len(self.im.by_status(s))
                for s in (QUEUED, REQUESTED, ALLOCATED, RAY_RUNNING,
                          ALLOCATION_FAILED, TERMINATING, TERMINATED)},
            "version": self.im.version,
        }
