"""ray_tpu — a TPU-native distributed ML framework.

Capability surface of Ray (tasks / actors / objects, Data, Train, Tune, Serve,
RL), re-designed TPU-first: the control plane is a lightweight native runtime
(controller + per-host supervisor + shared-memory object store), and the tensor
plane is JAX/XLA — device arrays move over ICI via XLA collectives under
``jax.sharding.Mesh``, never through the object store.
"""

__version__ = "0.1.0"

from ray_tpu._private.api import (  # noqa: F401
    ObjectRef,
    ObjectRefGenerator,
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    get_runtime_context,
    init,
    is_initialized,
    kill,
    method,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)
from ray_tpu._private.exceptions import (  # noqa: F401
    ActorDiedError,
    ActorUnavailableError,
    GetTimeoutError,
    ObjectLostError,
    RayTpuError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
