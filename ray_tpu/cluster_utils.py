"""Multi-node test cluster on one host.

Analog of the reference's ``python/ray/cluster_utils.py:135``: start a real
controller plus N real supervisor processes on one machine, so multi-node
semantics (scheduling, spillback, placement groups, node failure, object
transfer) are exercised with real process boundaries — the reference's core
integration-test pattern (SURVEY §4).
"""

from __future__ import annotations

import os
import subprocess
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.config import Config
from ray_tpu._private.node import start_controller, start_supervisor, new_session_dir

Address = Tuple[str, int]


class ClusterNode:
    def __init__(self, proc: subprocess.Popen, address: Address, name: str):
        self.proc = proc
        self.address = address
        self.name = name

    def kill(self) -> None:
        """Hard-kill the supervisor process (chaos testing)."""
        try:
            self.proc.kill()
            self.proc.wait(timeout=5)
        except Exception:
            pass


class Cluster:
    """≈ ray.cluster_utils.Cluster (add_node :201, remove_node :274)."""

    def __init__(self, config: Optional[Config] = None):
        self.config = config or Config.from_env()
        self.session_dir = new_session_dir()
        self.controller_proc, self.controller_addr = start_controller(
            self.session_dir, self.config
        )
        self.nodes: List[ClusterNode] = []

    @property
    def address(self) -> str:
        return f"{self.controller_addr[0]}:{self.controller_addr[1]}"

    def add_node(
        self,
        num_cpus: float = 1,
        num_tpus: int = 0,
        resources: Optional[Dict[str, float]] = None,
        object_store_memory: Optional[int] = None,
        name: str = "",
        labels: Optional[Dict[str, str]] = None,
    ) -> ClusterNode:
        node_resources = {"CPU": float(num_cpus), "memory": 2.0 * 1024**3}
        if num_tpus:
            node_resources["TPU"] = float(num_tpus)
        if resources:
            node_resources.update({k: float(v) for k, v in resources.items()})
        name = name or f"node{len(self.nodes)}"
        proc, addr = start_supervisor(
            self.session_dir,
            self.config,
            self.controller_addr,
            resources=node_resources,
            node_name=name,
            labels=labels,
        )
        node = ClusterNode(proc, addr, name)
        self.nodes.append(node)
        return node

    def restart_controller(self) -> None:
        """Hard-kill the controller and start a replacement on the SAME
        address; it recovers actors/PGs/jobs/KV from the session-dir
        snapshot (controller fault-tolerance chaos testing)."""
        try:
            self.controller_proc.kill()
            self.controller_proc.wait(timeout=5)
        except Exception:
            pass
        # the dead controller's address file would satisfy the startup
        # wait immediately; remove it so we observe the NEW controller's
        # write (and actually detect a failed respawn)
        try:
            os.remove(os.path.join(self.session_dir, "controller_address"))
        except FileNotFoundError:
            pass
        self.controller_proc, addr = start_controller(
            self.session_dir, self.config, port=self.controller_addr[1]
        )
        assert addr == self.controller_addr, (addr, self.controller_addr)

    def remove_node(self, node: ClusterNode) -> None:
        node.kill()
        if node in self.nodes:
            self.nodes.remove(node)

    def wait_for_nodes(self, count: Optional[int] = None, timeout: float = 30) -> None:
        import asyncio

        from ray_tpu._private.rpc import RpcClient

        want = count if count is not None else len(self.nodes)

        async def poll():
            client = RpcClient(self.controller_addr)
            try:
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    views = await client.call("node_views")
                    if sum(1 for v in views if v["alive"]) >= want:
                        return
                    await asyncio.sleep(0.05)
                raise TimeoutError(f"cluster did not reach {want} alive nodes")
            finally:
                await client.close()

        asyncio.run(poll())

    def shutdown(self) -> None:
        for node in self.nodes:
            node.kill()
        self.nodes.clear()
        try:
            self.controller_proc.terminate()
            self.controller_proc.wait(timeout=3)
        except Exception:
            try:
                self.controller_proc.kill()
            except Exception:
                pass
