"""Dynamic request batching.

Analog of `ray.serve.batching.batch` (`python/ray/serve/batching.py`):
decorate an async method taking a LIST of items; concurrent callers (the
replica runs requests concurrently on one asyncio loop) are coalesced
into batches of up to `max_batch_size`, flushed when full or after
`batch_wait_timeout_s`.

This is the GENERIC (request-level) batcher: one flush runs its whole
batch to completion before results resolve. The LLM decode path no
longer rides it — `serve/_private/continuous.py` admits and retires
sequences at decode-iteration granularity — but it remains the right
tool for stateless batchable work (embedding lookups, rerankers, vision
encoders) where per-item latency ≈ batch latency.

Error semantics: if the batch fn raises, every waiter in that flush gets
the exception; if it returns normally, any `Exception` INSTANCE in the
output list is routed to just its own waiter (per-item error isolation —
one poisoned input no longer fails its batchmates).
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int, timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._timeout = timeout_s
        self._items: List[Any] = []
        self._futures: List[asyncio.Future] = []
        self._flush_task: Optional[asyncio.Task] = None
        self._self_obj = None

    async def submit(self, self_obj, item: Any) -> Any:
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._self_obj = self_obj
        self._items.append(item)
        self._futures.append(fut)
        if len(self._items) >= self._max:
            self._flush_now()
        elif self._flush_task is None:
            self._flush_task = loop.create_task(self._flush_later())
        return await fut

    async def _flush_later(self):
        try:
            await asyncio.sleep(self._timeout)
        except asyncio.CancelledError:
            # a full-batch flush consumed our batch between scheduling and
            # expiry — nothing left to do
            return
        if self._flush_task is not asyncio.current_task():
            # stale timer: a full-batch flush raced our wakeup (its
            # cancel() landed after our sleep completed but before we ran)
            # and a NEW batch may already own a new timer — flushing here
            # would flush the new batch early, or double-flush
            return
        self._flush_now()

    def _flush_now(self):
        # clear the timer handle BEFORE flushing, so a submit() landing
        # while _run_batch is in flight arms a fresh timer for the next
        # batch instead of seeing a dead task
        task, self._flush_task = self._flush_task, None
        if task is not None and task is not asyncio.current_task():
            task.cancel()
        items, futures = self._items, self._futures
        self._items, self._futures = [], []
        if not items:
            return
        asyncio.ensure_future(self._run_batch(items, futures))

    async def _run_batch(self, items, futures):
        try:
            if self._self_obj is not None:
                outs = await self._fn(self._self_obj, items)
            else:
                outs = await self._fn(items)
            if outs is None or len(outs) != len(items):
                raise ValueError(
                    f"batch fn returned "
                    f"{'None' if outs is None else len(outs)} results for "
                    f"{len(items)} inputs")
            for f, o in zip(futures, outs):
                if f.done():
                    continue
                if isinstance(o, Exception):
                    # per-item failure: only this waiter sees it
                    f.set_exception(o)
                else:
                    f.set_result(o)
        except BaseException as e:
            for f in futures:
                if not f.done():
                    f.set_exception(e)


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    def decorator(fn: Callable):
        if not asyncio.iscoroutinefunction(fn):
            raise TypeError("@serve.batch requires an async function")
        queue_attr = f"__serve_batch_queue_{fn.__name__}"

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:  # bound method: (self, item)
                self_obj, item = args
                q = getattr(self_obj, queue_attr, None)
                if q is None:
                    # instances may override the decorator defaults
                    # (e.g. a model replica configured at deploy time)
                    size = getattr(
                        self_obj, "__serve_batch_size_" + fn.__name__,
                        max_batch_size)
                    timeout = getattr(
                        self_obj, "__serve_batch_timeout_" + fn.__name__,
                        batch_wait_timeout_s)
                    q = _BatchQueue(fn, size, timeout)
                    setattr(self_obj, queue_attr, q)
                return await q.submit(self_obj, item)
            (item,) = args
            q = wrapper.__dict__.setdefault(
                "_queue", _BatchQueue(fn, max_batch_size,
                                      batch_wait_timeout_s))
            return await q.submit(None, item)

        return wrapper

    return decorator(_fn) if _fn is not None else decorator
