"""Model multiplexing — many models per deployment, LRU-cached per replica.

Analog of `python/ray/serve/multiplex.py` (`@serve.multiplexed`) +
`_ModelMultiplexWrapper`: a decorated `load_model(self, model_id)` becomes
an LRU cache of live models; requests carry `multiplexed_model_id` (set via
`handle.options(multiplexed_model_id=...)`), the router prefers replicas
that already hold the model (falling back to pow-2 on misses), and
`serve.get_multiplexed_model_id()` exposes the id inside the request.

Divergence from the reference: model locations reach the router by a
lightweight poll of replica `multiplex_info` (only while multiplexed
requests flow) instead of the controller long-poll channel — same
preference semantics, one less controller hop.
"""

from __future__ import annotations

import asyncio
import contextvars
import functools
import inspect
from collections import OrderedDict
from typing import Any, Callable, Optional

_request_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")

#: attribute on the user callable instance holding the LRU cache — the
#: replica reads it to report loaded model ids
MUX_ATTR = "__serve_mux_models__"


def get_multiplexed_model_id() -> str:
    """Inside a request: the model id this request was routed with
    (≈ `serve.get_multiplexed_model_id`)."""
    return _request_model_id.get()


def _set_request_model_id(model_id: Optional[str]):
    if model_id:
        return _request_model_id.set(model_id)
    return None


def multiplexed(max_num_models_per_replica: int = 3) -> Callable:
    """Decorator for an async (or sync) `load_model(self, model_id)`
    method; calls become LRU-cached model lookups."""

    def decorate(load_fn: Callable) -> Callable:
        is_method = "self" in inspect.signature(load_fn).parameters

        async def _load(owner, model_id: str):
            cache: OrderedDict = getattr(owner, MUX_ATTR, None)
            if cache is None:
                cache = OrderedDict()
                setattr(owner, MUX_ATTR, cache)
                owner.__serve_mux_loading__ = {}
            # fast path: hits never wait behind another model's cold load
            if model_id in cache:
                cache.move_to_end(model_id)
                return cache[model_id]
            # dedupe concurrent loads of the SAME model; different models
            # load concurrently (reference _ModelMultiplexWrapper semantics)
            loading: dict = owner.__serve_mux_loading__
            fut = loading.get(model_id)
            if fut is None:
                async def do_load():
                    try:
                        out = (load_fn(owner, model_id) if is_method
                               else load_fn(model_id))
                        if inspect.isawaitable(out):
                            out = await out
                        # cache inside the load task: the result must land
                        # even if every waiter was cancelled meanwhile
                        cache[model_id] = out
                        while len(cache) > max_num_models_per_replica:
                            cache.popitem(last=False)  # evict LRU; GC unloads
                        return out
                    finally:
                        loading.pop(model_id, None)

                fut = asyncio.ensure_future(do_load())
                loading[model_id] = fut
            # every waiter (leader included) shields: one cancelled request
            # must not cancel the shared load out from under the others
            return await asyncio.shield(fut)

        if is_method:
            @functools.wraps(load_fn)
            async def wrapper(self, model_id: str):
                return await _load(self, model_id)
        else:
            # bare function deployments: cache lives on the function object
            @functools.wraps(load_fn)
            async def wrapper(model_id: str):
                return await _load(wrapper, model_id)

        wrapper.__is_multiplexed__ = True
        return wrapper

    return decorate


def loaded_model_ids(user_callable: Any) -> list:
    """Model ids currently cached on a replica's callable (newest last)."""
    cache = getattr(user_callable, MUX_ATTR, None)
    return list(cache.keys()) if cache else []
