"""DeploymentHandle — composable client to a deployment.

Analog of `ray.serve.handle.DeploymentHandle`: `handle.remote(...)`
returns a `DeploymentResponse` (resolve with `.result()`, await it, or
pass the underlying ref onward). Method access (`handle.other.remote()`)
routes to that method of the callable. A deployment method that returns
a (sync or async) generator streams: iterate the response
(`for chunk in handle.remote(...)`) to pull chunks as they are produced
(≈ handle.options(stream=True) in the reference).
"""

from __future__ import annotations

from typing import Any, Iterator, Optional

import ray_tpu
from ray_tpu.serve._private.router import Router

STREAM_MARKER = "__serve_stream__"


class DeploymentResponse:
    def __init__(self, ref, replica=None):
        self._ref = ref
        self._replica = replica

    def result(self, timeout: Optional[float] = None) -> Any:
        if isinstance(self._ref, ray_tpu.ObjectRefGenerator):
            raise TypeError(
                "streaming response (handle.options(stream=True)): iterate "
                "it instead of calling .result()")
        return ray_tpu.get(self._ref, timeout=timeout)

    def __await__(self):
        if isinstance(self._ref, ray_tpu.ObjectRefGenerator):
            raise TypeError(
                "streaming response (handle.options(stream=True)): use "
                "'async for' instead of awaiting it")
        return self._ref.__await__()

    def __aiter__(self):
        """Async streaming: async-for over chunks (each awaited get)."""
        async def agen():
            if isinstance(self._ref, ray_tpu.ObjectRefGenerator):
                async for chunk_ref in self._ref:
                    yield await chunk_ref
                return
            out = await self._ref
            if isinstance(out, dict) and STREAM_MARKER in out:
                raise TypeError("chunk-pull streams are sync-iterate only; "
                                "use handle.options(stream=True) for async")
            yield out

        return agen()

    @property
    def ref(self):
        return self._ref

    def __iter__(self) -> Iterator[Any]:
        """Stream the response. Non-streaming results yield once."""
        if isinstance(self._ref, ray_tpu.ObjectRefGenerator):
            # native generator transport (handle.options(stream=True)):
            # chunks are owner-owned refs arriving as produced
            for chunk_ref in self._ref:
                yield ray_tpu.get(chunk_ref)
            return
        out = self.result()
        if not (isinstance(out, dict) and STREAM_MARKER in out):
            yield out
            return
        if self._replica is None:
            raise RuntimeError("streaming response without replica binding")
        sid = out[STREAM_MARKER]
        while True:
            chunk = ray_tpu.get(self._replica.stream_next.remote(sid))
            for item in chunk["items"]:
                yield item
            if chunk.get("error"):
                raise RuntimeError(f"stream failed: {chunk['error']}")
            if chunk["done"]:
                return


class _BoundMethod:
    def __init__(self, handle: "DeploymentHandle", method_name: str):
        self._handle = handle
        self._method = method_name

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._call(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(self, app_name: str, deployment_name: str,
                 controller=None, multiplexed_model_id: str = "",
                 stream: bool = False):
        self._app = app_name
        self._deployment = deployment_name
        self._controller = controller
        self._router: Optional[Router] = None
        self._mux_id = multiplexed_model_id
        self._stream = stream

    def options(self, *, multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        """≈ `serve.handle.DeploymentHandle.options`: a copy of this handle
        whose requests carry (and route by) the multiplexed model id
        and/or stream via the native generator transport (stream=True,
        ≈ the reference's handle.options(stream=True)). Unspecified
        options keep their current values, so chained .options() calls
        compose."""
        h = DeploymentHandle(
            self._app, self._deployment, self._controller,
            multiplexed_model_id=(self._mux_id if multiplexed_model_id
                                  is None else multiplexed_model_id),
            stream=self._stream if stream is None else stream)
        # share ONE router (and its replica view + affinity state) across
        # all options() copies — materialize it now so per-request
        # h.options(...) calls don't each build a router + poll threads
        h._router = self._get_router()
        return h

    def _get_router(self) -> Router:
        if self._router is None:
            controller = self._controller
            if controller is None:
                from ray_tpu.serve._private.controller import CONTROLLER_NAME

                controller = ray_tpu.get_actor(CONTROLLER_NAME)
                self._controller = controller
            self._router = Router(controller, self._app, self._deployment)
        return self._router

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._call("__call__", args, kwargs)

    def _call(self, method: str, args, kwargs) -> DeploymentResponse:
        # resolve nested responses so chained models compose
        args = tuple(a._ref if isinstance(a, DeploymentResponse) else a
                     for a in args)
        kwargs = {k: (v._ref if isinstance(v, DeploymentResponse) else v)
                  for k, v in kwargs.items()}
        if self._mux_id:
            kwargs = dict(kwargs, __serve_mux_id=self._mux_id)
        ref, replica = self._get_router().assign_request_with_replica(
            method, args, kwargs, multiplexed_model_id=self._mux_id,
            streaming=self._stream)
        return DeploymentResponse(ref, replica=replica)

    def __getattr__(self, name: str) -> _BoundMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return _BoundMethod(self, name)

    def __reduce__(self):
        return (DeploymentHandle,
                (self._app, self._deployment, None, self._mux_id,
                 self._stream))
