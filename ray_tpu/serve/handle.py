"""DeploymentHandle — composable client to a deployment.

Analog of `ray.serve.handle.DeploymentHandle`: `handle.remote(...)`
returns a `DeploymentResponse` (resolve with `.result()`, await it, or
pass the underlying ref onward). Method access (`handle.other.remote()`)
routes to that method of the callable.
"""

from __future__ import annotations

from typing import Any, Optional

import ray_tpu
from ray_tpu.serve._private.router import Router


class DeploymentResponse:
    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout: Optional[float] = None) -> Any:
        return ray_tpu.get(self._ref, timeout=timeout)

    def __await__(self):
        return self._ref.__await__()

    @property
    def ref(self):
        return self._ref


class _BoundMethod:
    def __init__(self, handle: "DeploymentHandle", method_name: str):
        self._handle = handle
        self._method = method_name

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._call(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(self, app_name: str, deployment_name: str,
                 controller=None):
        self._app = app_name
        self._deployment = deployment_name
        self._controller = controller
        self._router: Optional[Router] = None

    def _get_router(self) -> Router:
        if self._router is None:
            controller = self._controller
            if controller is None:
                from ray_tpu.serve._private.controller import CONTROLLER_NAME

                controller = ray_tpu.get_actor(CONTROLLER_NAME)
                self._controller = controller
            self._router = Router(controller, self._app, self._deployment)
        return self._router

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._call("__call__", args, kwargs)

    def _call(self, method: str, args, kwargs) -> DeploymentResponse:
        # resolve nested responses so chained models compose
        args = tuple(a._ref if isinstance(a, DeploymentResponse) else a
                     for a in args)
        kwargs = {k: (v._ref if isinstance(v, DeploymentResponse) else v)
                  for k, v in kwargs.items()}
        ref = self._get_router().assign_request(method, args, kwargs)
        return DeploymentResponse(ref)

    def __getattr__(self, name: str) -> _BoundMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return _BoundMethod(self, name)

    def __reduce__(self):
        return (DeploymentHandle, (self._app, self._deployment))
