"""ray_tpu.serve — model serving (Ray Serve analog, `python/ray/serve/`).

`@serve.deployment` → `.bind()` → `serve.run()`; replicas are async
actors behind a power-of-two-choices router; an aiohttp proxy provides
HTTP ingress; the controller reconciles replica counts and autoscales on
in-flight requests (`serve.run` call stack: SURVEY §3.5).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import ray_tpu
from ray_tpu.serve._private.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.asgi import ingress  # noqa: F401
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse  # noqa: F401
from ray_tpu.serve.multiplex import (  # noqa: F401
    get_multiplexed_model_id,
    multiplexed,
)


def start_rpc_ingress(port: int = 0) -> int:
    """Start the binary RPC ingress (the reference's gRPC-ingress role over
    the framework's native framing); returns the bound port."""
    from ray_tpu.serve._private.rpc_ingress import start_rpc_ingress as _s

    return _s(port)


@dataclasses.dataclass
class AutoscalingConfig:
    """Analog of `ray.serve.config.AutoscalingConfig`."""

    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    # retire nodes fully vacated by an autoscaler scale-down via the
    # controller's node_drain RPC (immediate channel/pin/lease handoff,
    # no crash debounce). Opt-in: a drain takes the whole node, so this
    # is only safe when the autoscaled replica pool owns its nodes.
    drain_nodes: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class Application:
    """A bound deployment graph node (reference: `Deployment.bind`
    `python/ray/serve/deployment.py:245`)."""

    def __init__(self, deployment: "Deployment", args: Tuple, kwargs: Dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs


class Deployment:
    def __init__(self, func_or_class: Any, name: str,
                 num_replicas: int = 1,
                 max_ongoing_requests: int = 8,
                 ray_actor_options: Optional[Dict] = None,
                 autoscaling_config: Optional[Union[Dict,
                                                    AutoscalingConfig]] = None,
                 user_config: Any = None):
        self.func_or_class = func_or_class
        self.name = name
        self.num_replicas = num_replicas
        self.max_ongoing_requests = max_ongoing_requests
        self.ray_actor_options = ray_actor_options or {}
        if isinstance(autoscaling_config, AutoscalingConfig):
            autoscaling_config = autoscaling_config.to_dict()
        self.autoscaling_config = autoscaling_config
        self.user_config = user_config

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def options(self, **overrides) -> "Deployment":
        fields = dict(
            func_or_class=self.func_or_class, name=self.name,
            num_replicas=self.num_replicas,
            max_ongoing_requests=self.max_ongoing_requests,
            ray_actor_options=dict(self.ray_actor_options),
            autoscaling_config=self.autoscaling_config,
            user_config=self.user_config)
        fields.update(overrides)
        return Deployment(**fields)

    def _spec(self, init_args: Tuple, init_kwargs: Dict) -> Dict[str, Any]:
        cls = self.func_or_class
        num = self.num_replicas
        if self.autoscaling_config:
            num = max(num, self.autoscaling_config.get("min_replicas", 1))
        return {
            "name": self.name,
            "num_replicas": num,
            "max_ongoing_requests": self.max_ongoing_requests,
            "ray_actor_options": self.ray_actor_options,
            "autoscaling_config": self.autoscaling_config,
            "user_config": self.user_config,
            "callable_factory": lambda: cls,
            "init_args": init_args,
            "init_kwargs": init_kwargs,
        }


def deployment(_func_or_class: Optional[Any] = None, *,
               name: Optional[str] = None,
               num_replicas: int = 1,
               max_ongoing_requests: int = 8,
               ray_actor_options: Optional[Dict] = None,
               autoscaling_config: Optional[Union[Dict,
                                                  AutoscalingConfig]] = None,
               user_config: Any = None):
    """`@serve.deployment` (reference `python/ray/serve/api.py`)."""

    def wrap(fc):
        return Deployment(fc, name or fc.__name__,
                          num_replicas=num_replicas,
                          max_ongoing_requests=max_ongoing_requests,
                          ray_actor_options=ray_actor_options,
                          autoscaling_config=autoscaling_config,
                          user_config=user_config)

    return wrap(_func_or_class) if _func_or_class is not None else wrap


# ----------------------------------------------------------------- control


def _get_or_create_controller():
    try:
        c = ray_tpu.get_actor(CONTROLLER_NAME)
        # the name registry may still hold a controller a previous
        # serve.shutdown killed — liveness-check before trusting it
        ray_tpu.get(c.get_routes.remote(), timeout=10)
        return c
    except Exception:
        return ray_tpu.remote(ServeController).options(
            name=CONTROLLER_NAME, lifetime="detached", num_cpus=0.1,
            max_concurrency=256).remote()


def _collect_specs(app: Application, specs: Dict[str, Dict],
                   ) -> DeploymentHandle:
    """DFS the bind graph; nested Applications become DeploymentHandles."""
    dep = app.deployment

    def resolve(v):
        if isinstance(v, Application):
            return _collect_specs(v, specs)
        return v

    init_args = tuple(resolve(a) for a in app.args)
    init_kwargs = {k: resolve(v) for k, v in app.kwargs.items()}
    if dep.name not in specs:
        specs[dep.name] = dep._spec(init_args, init_kwargs)
    return DeploymentHandle(_current_app_name, dep.name)


_current_app_name = "default"


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = "/", _blocking: bool = True,
        timeout_s: float = 60.0) -> DeploymentHandle:
    global _current_app_name
    _current_app_name = name
    controller = _get_or_create_controller()
    specs: Dict[str, Dict] = {}
    ingress_handle = _collect_specs(app, specs)
    ray_tpu.get(controller.deploy_application.remote(
        name, list(specs.values()), route_prefix, app.deployment.name))
    if _blocking:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            st = ray_tpu.get(controller.status.remote()).get(name, {})
            if st and all(d["status"] == "RUNNING" for d in st.values()):
                break
            time.sleep(0.1)
        else:
            raise TimeoutError(f"application {name!r} not RUNNING: {st}")
    ingress_handle._controller = controller
    return ingress_handle


def start(*, http_port: int = 8000) -> int:
    """Start the HTTP proxy (reference starts proxies on serve.start /
    first run; explicit here). Returns the bound port."""
    from ray_tpu.serve._private.proxy import ProxyActor

    controller = _get_or_create_controller()
    try:
        proxy = ray_tpu.get_actor("SERVE_PROXY")
    except Exception:
        proxy = ray_tpu.remote(ProxyActor).options(
            name="SERVE_PROXY", lifetime="detached", num_cpus=0.1,
            max_concurrency=256).remote(controller, http_port)
    return ray_tpu.get(proxy.ready.remote())


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    routes = ray_tpu.get(controller.get_routes.remote())
    for target in routes.values():
        app_name, dep = target.split("/", 1)
        if app_name == name:
            h = DeploymentHandle(app_name, dep, controller)
            return h
    raise ValueError(f"no application named {name!r}")


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    return DeploymentHandle(app_name, deployment_name, controller)


def status() -> Dict[str, Any]:
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    return ray_tpu.get(controller.status.remote())


def delete(name: str) -> None:
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    ray_tpu.get(controller.delete_application.remote(name))


def shutdown() -> None:
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return
    try:
        ray_tpu.get(controller.graceful_shutdown.remote())
    except Exception:
        pass
    for actor_name in ("SERVE_PROXY", "SERVE_RPC_INGRESS", CONTROLLER_NAME):
        try:
            ray_tpu.kill(ray_tpu.get_actor(actor_name))
        except Exception:
            pass

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu("serve")
del _rlu
