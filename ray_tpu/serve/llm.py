"""LLM decode deployment — the Serve flagship (BASELINE.md row 5).

The reference leaves model serving to torch/vLLM inside replicas (its
`ray.serve.llm` wraps vLLM engines); here the decode loop is TPU-native
and the batching is CONTINUOUS (iteration-level, ISSUE 9):

  * a PAGED KV arena (`models.decode.PagedKVCache`, ISSUE 13) plus ONE
    fixed-shape jitted decode step over all slots per iteration; slots
    own page tables instead of worst-case `max_seq_len` ranges, a radix
    prefix cache turns shared system-prompt/few-shot preambles into a
    page-table splice + cursor jump at admission, new requests are
    admitted into free slots between iterations (chunked prefill),
    finished/EOS/cancelled sequences retire their slot (and pages)
    immediately — ≈ vLLM's PagedAttention + SGLang's RadixAttention
    scheduling, not a flush-and-drain `@serve.batch` window (kept as
    `scheduler="batch"`, the measured baseline; `kv_layout="contiguous"`
    keeps the PR-9 arena);
  * token streaming: `{"prompt": ..., "stream": true}` returns an async
    generator consuming the scheduler's per-slot token queue — the stream
    rides the same batched program as everything else (no per-stream
    single-sequence decode loop, nothing jitted ever runs on the
    replica's asyncio event loop);
  * one-copy-per-node weights: the first replica on a node publishes the
    params into the shared-memory object arena; later same-node replicas
    attach pinned read-only views (serve/_private/weights.py), and new
    nodes can receive the tree over `collective.broadcast`
    (`push_weights`) so scale-up is seconds, not checkpoint-staging
    minutes;
  * replica autoscaling/health from the regular serve control plane.

The default preset is `llama_debug` (random weights) so the deployment
is runnable anywhere; pass `preset="llama3_8b"` plus a checkpoint
loader for the real thing.
"""

from __future__ import annotations

import asyncio
from functools import partial
from typing import Any, Dict, List, Optional

import ray_tpu.serve as serve
from ray_tpu.models import presets
from ray_tpu.models.decode import (decode_step, init_caches, prefill,
                                   sample_token)


def _byte_tokenize(text: str, vocab_size: int) -> List[int]:
    """Byte-level toy tokenizer (debug presets have vocab >= 256). Real
    deployments pass `tokenize`/`detokenize` callables to LLMServer."""
    return [b % vocab_size for b in text.encode("utf-8")]


def _byte_detokenize(ids: List[int]) -> str:
    return bytes(int(i) % 256 for i in ids).decode("utf-8", errors="replace")


class LLMServerImpl:
    """One model replica: owns the jitted decode programs and (in
    continuous mode) the slot-arena scheduler. Weights are shared per node
    through the object arena unless ``share_weights=False``."""

    def __init__(self, preset: str = "llama_debug",
                 preset_overrides: Optional[Dict[str, Any]] = None,
                 max_new_tokens: int = 16,
                 temperature: float = 0.0,
                 max_batch_size: int = 8,
                 params_loader=None,
                 tokenize=None, detokenize=None,
                 scheduler: str = "continuous",
                 slots: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 arena_len: Optional[int] = None,
                 kv_layout: Optional[str] = None,
                 page_tokens: Optional[int] = None,
                 kv_pages: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 share_weights: bool = True,
                 weights_key: Optional[str] = None,
                 weights_bcast: Optional[Dict[str, Any]] = None,
                 eos_id: Optional[int] = None,
                 drafter: Optional[str] = None,
                 spec_k: Optional[int] = None,
                 migration_budget: Optional[int] = None,
                 attn: Optional[str] = None):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.transformer import init_params

        if scheduler not in ("continuous", "batch"):
            raise ValueError(
                f"scheduler must be 'continuous' or 'batch', got "
                f"{scheduler!r}")
        self._jnp = jnp
        self._jax = jax
        # preset fields (e.g. a wider max_seq_len context window for long
        # few-shot preambles) are overridable per deployment; the KV arena
        # and admission limits follow cfg.max_seq_len automatically
        self.cfg = getattr(presets, preset)(**(preset_overrides or {}))
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self._max_batch = max_batch_size
        self._scheduler_mode = scheduler
        self._eos_id = eos_id
        self._seq_counter = 0

        # ---- weights: one arena copy per node (ISSUE 9 tentpole) ----
        from ray_tpu.serve._private import weights as _weights

        def load():
            if weights_bcast is not None and weights_bcast.get("rank", 0) != \
                    weights_bcast.get("root", 0):
                # fresh node: receive the tree from an existing replica
                # instead of staging a checkpoint
                return _weights.broadcast_params(
                    None, weights_bcast["group"],
                    int(weights_bcast["world_size"]),
                    int(weights_bcast["rank"]),
                    root=int(weights_bcast.get("root", 0)))
            if params_loader is not None:
                return params_loader(self.cfg)
            return init_params(self.cfg, jax.random.PRNGKey(0))

        # a custom loader has no stable identity to share under; require an
        # explicit weights_key to opt in
        can_share = share_weights and (params_loader is None
                                       or weights_key is not None)
        if can_share:
            # preset overrides change the parameter shapes — fold them
            # into the default share key so differently-configured
            # deployments never attach to each other's arena copy
            ov = ""
            if preset_overrides:
                ov = ":" + ",".join(f"{k}={preset_overrides[k]}"
                                    for k in sorted(preset_overrides))
            key = weights_key or f"llm:{preset}{ov}:seed0"
            host, self._weights_info = _weights.get_or_publish(key, load)
        else:
            host, self._weights_info = load(), {"mode": "local",
                                                "shared": False}
        # one device copy per replica (HBM on TPU); the HOST copy stays
        # shared in the node arena — self._host_params keeps the read-only
        # views (and their pins) alive for this replica's lifetime
        self._host_params = host
        self.params = jax.device_put(host)
        del host

        self._tokenize = tokenize or partial(
            _byte_tokenize, vocab_size=self.cfg.vocab_size)
        self._detokenize = detokenize or _byte_detokenize
        # the router can only steer (prefix affinity) on prompts it can
        # tokenize itself — true for the reproducible byte tokenizer;
        # custom tokenizers need explicit prompt_ids in the request
        self._byte_tok = tokenize is None
        # jitted programs for the request-level baseline + legacy streaming
        self._prefill = jax.jit(partial(prefill, self.cfg))
        self._decode_step = jax.jit(partial(decode_step, self.cfg))
        self._key = jax.random.PRNGKey(0)
        import threading

        self._key_lock = threading.Lock()  # batch flushes run on executor threads
        # deploy-time batch size overrides the @serve.batch default
        setattr(self, "__serve_batch_size__generate_batch", max_batch_size)

        self._sched = None
        if scheduler == "continuous":
            from ray_tpu.serve._private.continuous import ContinuousScheduler

            drafter_obj = self._build_drafter(drafter, slots, arena_len,
                                              _weights)
            self._sched = ContinuousScheduler(
                self.cfg, self.params, slots=slots,
                prefill_chunk=prefill_chunk, arena_len=arena_len,
                eos_id=eos_id, kv_layout=kv_layout,
                page_tokens=page_tokens, kv_pages=kv_pages,
                prefix_cache=prefix_cache, drafter=drafter_obj,
                spec_k=spec_k, migration_budget=migration_budget,
                attn=attn)
        elif drafter:
            raise ValueError(
                "speculative decoding (drafter=...) requires "
                "scheduler='continuous'")
        elif attn is not None:
            raise ValueError(
                "attn lane selection (attn=...) requires "
                "scheduler='continuous' with kv_layout='paged'")

    def _build_drafter(self, drafter: Optional[str], slots, arena_len,
                       _weights):
        """Resolve the drafter knob (arg, else RAY_TPU_SERVE_DRAFTER; ""
        = off) into a ``speculative.Drafter``. ``"self"`` reuses this
        replica's own device params (zero extra weight memory, KV adopted
        from the paged cache); any other name is a preset whose weights
        come from the shared per-node arena like the target's
        (``get_or_publish``) — a drafter must share the target's
        vocabulary or its proposals would be meaningless token ids."""
        import jax

        from ray_tpu._private.config import global_config
        from ray_tpu.models.transformer import init_params

        conf = global_config()
        name = conf.serve_drafter if drafter is None else drafter
        if not name:
            return None
        slots_r = int(conf.serve_slots if slots is None else slots)
        arena_r = int(self.cfg.max_seq_len if arena_len is None
                      else arena_len)
        if name == "self":
            d_cfg, d_params, shares = self.cfg, self.params, True
        else:
            try:
                d_cfg = getattr(presets, name)()
            except AttributeError:
                raise ValueError(f"unknown drafter preset {name!r}")
            if d_cfg.vocab_size != self.cfg.vocab_size:
                raise ValueError(
                    f"drafter {name!r} vocab_size ({d_cfg.vocab_size}) != "
                    f"target vocab_size ({self.cfg.vocab_size})")
            d_host, self._drafter_weights_info = _weights.get_or_publish(
                f"llm:{name}:seed0",
                lambda: init_params(d_cfg, jax.random.PRNGKey(0)))
            self._drafter_host_params = d_host
            d_params = jax.device_put(d_host)
            shares = False
        if arena_r > d_cfg.max_seq_len:
            raise ValueError(
                f"drafter {name!r} max_seq_len ({d_cfg.max_seq_len}) is "
                f"shorter than the serving arena ({arena_r})")
        from ray_tpu.serve._private.speculative import Drafter

        return Drafter(d_cfg, d_params, slots=slots_r, arena_len=arena_r,
                       name=name, shares_target=shares)

    # ------------------------------------------------------- continuous

    def _submit(self, ids: List[int], max_new: int, temperature: float,
                fleet_hint=None):
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()
        self._seq_counter += 1
        seq = self._sched.submit(
            ids, max_new_tokens=max_new, temperature=temperature,
            seed=self._seq_counter, loop=loop, queue=q,
            fleet_hint=fleet_hint)
        return seq, q

    async def _run_continuous(self, ids: List[int], max_new: int,
                              temperature: float,
                              fleet_hint=None) -> List[int]:
        seq, q = self._submit(ids, max_new, temperature, fleet_hint)
        toks: List[int] = []
        try:
            while True:
                kind, val = await q.get()
                if kind == "tok":
                    toks.append(val)
                elif kind == "end":
                    return toks
                else:
                    raise RuntimeError(f"generation failed: {val}")
        except asyncio.CancelledError:
            self._sched.cancel(seq)
            raise

    async def _stream_continuous(self, ids: List[int], max_new: int,
                                 temperature: float, fleet_hint=None):
        """Streaming = a consumer of the scheduler's per-slot token queue.
        Abandoning the generator (consumer gone) cancels the sequence,
        which retires its slot on the scheduler's next iteration."""
        seq, q = self._submit(ids, max_new, temperature, fleet_hint)
        try:
            while True:
                kind, val = await q.get()
                if kind == "tok":
                    yield self._detokenize([val])
                elif kind == "end":
                    return
                else:
                    raise RuntimeError(f"generation failed: {val}")
        finally:
            self._sched.cancel(seq)

    # ------------------------------------------------ request-level path
    # (the measured flush-and-drain baseline: one @serve.batch window runs
    # prefill + the FULL decode loop before any newly arrived request is
    # admitted — scheduler="batch" keeps it selectable, exactly like the
    # collective layer's algo="kv")

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.02)
    async def _generate_batch(self, items) -> List[List[int]]:
        """Request-level batching: the flush runs every request in it to
        completion. The jax work runs on an executor thread — blocking the
        replica's event loop would stall health checks and stream pulls."""
        return await asyncio.get_running_loop().run_in_executor(
            None, self._generate_batch_sync, items)

    def _generate_batch_sync(self, items) -> List[List[int]]:
        """Group prompts by exact length and run one decode program per
        group. Padding mixed lengths into one program would let real tokens
        attend to pad positions (the causal cache mask has no pad masking),
        silently degrading shorter prompts; grouping keeps every program
        exact while still batching the common same-shape case."""
        by_len: Dict[int, List[int]] = {}
        for i, (p, _new) in enumerate(items):
            by_len.setdefault(len(p), []).append(i)
        outs: List[List[int]] = [[] for _ in items]
        for _length, indices in by_len.items():
            group = [items[i][0] for i in indices]
            # flush-and-drain: the whole group decodes until its LONGEST
            # request is done; shorter requests are truncated after
            steps = max(items[i][1] for i in indices)
            for i, out in zip(indices, self._generate_group(group, steps)):
                outs[i] = out[: items[i][1]]
        return outs

    def _generate_group(self, prompts: List[List[int]],
                        new_tokens: int) -> List[List[int]]:
        """One batched decode program over same-length prompts."""
        jnp = self._jnp
        batch = len(prompts)
        length = len(prompts[0])
        tokens = jnp.asarray(prompts, dtype=jnp.int32)
        caches = init_caches(self.cfg, batch, length + new_tokens)
        logits, caches = self._prefill(self.params, tokens, caches)
        outs: List[List[int]] = [[] for _ in range(batch)]
        for _ in range(new_tokens):
            with self._key_lock:
                self._key, sub = self._jax.random.split(self._key)
            tok = sample_token(logits, sub, self.temperature)
            for i, t in enumerate(tok.tolist()):
                outs[i].append(int(t))
            logits, caches = self._decode_step(
                self.params, tok[:, None].astype(jnp.int32), caches)
        return outs

    def _generate_stream(self, prompt_ids: List[int], new_tokens: int):
        """Legacy streaming (scheduler="batch" only): a single-sequence
        decode loop owning its own KV cache. The replica pumps it on an
        executor thread, never the event loop — but each live stream still
        monopolizes one whole decode program; the continuous path replaces
        this with a queue consumer over the shared slot arena."""
        jnp = self._jnp
        tokens = jnp.asarray([prompt_ids], dtype=jnp.int32)
        caches = init_caches(self.cfg, 1, len(prompt_ids) + new_tokens)
        logits, caches = self._prefill(self.params, tokens, caches)
        key = self._jax.random.PRNGKey(len(prompt_ids))
        for _ in range(new_tokens):
            key, sub = self._jax.random.split(key)
            tok = sample_token(logits, sub, self.temperature)
            yield self._detokenize([int(tok[0])])
            logits, caches = self._decode_step(
                self.params, tok[:, None].astype(jnp.int32), caches)

    # ------------------------------------------------------------ entry

    async def __call__(self, request: Optional[Dict[str, Any]] = None):
        request = request or {}
        if isinstance(request, str):
            request = {"prompt": request}
        prompt = request.get("prompt", "")
        if request.get("prompt_ids") is not None:
            # explicit token ids (custom-tokenizer clients; also what the
            # affinity router hashed, so steering and execution agree)
            ids = [int(t) for t in request["prompt_ids"]]
        else:
            ids = self._tokenize(prompt)
        if not ids:
            raise ValueError("prompt must be non-empty")
        max_new = int(request.get("max_new_tokens", self.max_new_tokens))
        temperature = float(request.get("temperature", self.temperature))
        # router-attached pull hint (fleet hit on another replica); only
        # meaningful to the continuous scheduler
        fleet_hint = request.get("_fleet_hint")
        if self._sched is not None:
            if request.get("stream"):
                return self._stream_continuous(ids, max_new, temperature,
                                               fleet_hint)
            out_ids = await self._run_continuous(ids, max_new, temperature,
                                                 fleet_hint)
        else:
            # the request-level path has no per-sequence cache bound of its
            # own (the continuous scheduler validates at submit): guard the
            # user-controlled budget before it sizes a KV cache, and refuse
            # (rather than silently ignore) per-request temperatures its
            # whole-batch sampler cannot honor
            if max_new < 1:
                raise ValueError("max_new_tokens must be >= 1")
            if len(ids) + max_new > self.cfg.max_seq_len:
                raise ValueError(
                    f"prompt of {len(ids)} tokens + {max_new} new tokens "
                    f"exceeds cfg.max_seq_len ({self.cfg.max_seq_len})")
            if temperature != self.temperature:
                raise ValueError(
                    "per-request temperature requires the continuous "
                    "scheduler (this replica runs scheduler='batch')")
            if request.get("stream"):
                return self._generate_stream(ids, max_new)
            out_ids = await self._generate_batch((ids, max_new))
        return {"prompt": prompt, "text": self._detokenize(out_ids),
                "num_tokens": len(out_ids)}

    # ------------------------------------------------------ introspection

    def scheduler_stats(self) -> Dict[str, Any]:
        if self._sched is not None:
            return self._sched.stats()
        return {"mode": "batch", "max_batch_size": self._max_batch}

    def queue_depth(self) -> int:
        """Admitted-but-unscheduled sequences (the replica relays this
        into its stats so the controller can autoscale on backlog, not
        just in-flight counts)."""
        if self._sched is not None:
            return int(self._sched.stats().get("queue_depth", 0))
        return 0

    def prefix_digest(self) -> Dict[str, Any]:
        """The radix cache's chain-hash digest plus what the router needs
        to hash prompts the same way (tokenizer kind + vocab). Empty when
        there is nothing advertisable (batch scheduler, contiguous
        layout, prefix cache off)."""
        if self._sched is None:
            return {}
        probe = getattr(self._sched, "prefix_digest", None)
        d = probe() if callable(probe) else {}
        if d:
            d = dict(d)
            d["vocab_size"] = self.cfg.vocab_size
            d["tok"] = "byte" if self._byte_tok else "opaque"
        return d

    def export_prefix(self, tokens: List[int],
                      timeout_s: float = 30.0) -> Dict[str, Any]:
        """Peer-replica migration pull: the longest cached prefix of
        ``tokens`` as per-layer KV page arrays (replica→replica, never
        through the controller)."""
        if self._sched is None:
            return {"matched_len": 0, "page_tokens": 0, "k": [], "v": []}
        return self._sched.export_prefix(list(tokens), timeout_s=timeout_s)

    def weights_info(self) -> Dict[str, Any]:
        return dict(self._weights_info)

    def push_weights(self, group: str, world_size: int,
                     rank: int = 0) -> bool:
        """Root side of seconds-scale scale-up: broadcast this replica's
        weights to `world_size - 1` receivers (replicas starting on new
        nodes with ``weights_bcast={"group", "world_size", "rank"}``)."""
        from ray_tpu.serve._private import weights as _weights

        _weights.broadcast_params(self._host_params, group, world_size,
                                  rank, root=rank)
        return True

    def check_health(self) -> bool:
        if self._sched is not None and self._sched.closed:
            return False
        return self.params is not None

    def shutdown(self) -> None:
        if self._sched is not None:
            self._sched.shutdown()

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


LLMServer = serve.deployment(name="llm", max_ongoing_requests=32)(
    LLMServerImpl)


def build_app(preset: str = "llama_debug", num_replicas: int = 1,
              max_new_tokens: int = 16, temperature: float = 0.0,
              **kwargs) -> "serve.Application":
    """`serve.run(build_app(...), route_prefix="/llm")` — the deployable
    LLM decode application."""
    dep = LLMServer.options(num_replicas=num_replicas)
    return dep.bind(preset=preset, max_new_tokens=max_new_tokens,
                    temperature=temperature, **kwargs)
