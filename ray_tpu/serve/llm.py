"""LLM decode deployment — the Serve flagship (BASELINE.md row 5).

The reference leaves model serving to torch/vLLM inside replicas (its
`ray.serve.llm` wraps vLLM engines); here the decode loop is TPU-native:
jitted prefill + per-token jitted decode steps over the functional KV
caches in `ray_tpu.models.decode`, with

  * continuous batching: concurrent HTTP/handle requests coalesce via
    `@serve.batch` into one batched `generate` program per flush
    (≈ vLLM's batched engine step inside a Serve replica);
  * token streaming: `{"prompt": ..., "stream": true}` returns a
    generator — the replica pumps a jitted decode step per token and the
    proxy/handle stream chunks as they are produced;
  * replica autoscaling/health from the regular serve control plane.

The default preset is `llama_debug` (random weights) so the deployment
is runnable anywhere; pass `preset="llama3_8b"` plus a checkpoint
loader for the real thing.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional

import ray_tpu.serve as serve
from ray_tpu.models import presets
from ray_tpu.models.decode import (decode_step, init_caches, prefill,
                                   sample_token)


def _byte_tokenize(text: str, vocab_size: int) -> List[int]:
    """Byte-level toy tokenizer (debug presets have vocab >= 256). Real
    deployments pass `tokenize`/`detokenize` callables to LLMServer."""
    return [b % vocab_size for b in text.encode("utf-8")]


def _byte_detokenize(ids: List[int]) -> str:
    return bytes(int(i) % 256 for i in ids).decode("utf-8", errors="replace")


@serve.deployment(name="llm", max_ongoing_requests=32)
class LLMServer:
    """One model replica: owns params + the jitted prefill/decode programs."""

    def __init__(self, preset: str = "llama_debug",
                 max_new_tokens: int = 16,
                 temperature: float = 0.0,
                 max_batch_size: int = 8,
                 params_loader=None,
                 tokenize=None, detokenize=None):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.transformer import init_params

        self._jnp = jnp
        self._jax = jax
        self.cfg = getattr(presets, preset)()
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self._max_batch = max_batch_size
        self.params = (params_loader(self.cfg) if params_loader is not None
                       else init_params(self.cfg, jax.random.PRNGKey(0)))
        self._tokenize = tokenize or partial(
            _byte_tokenize, vocab_size=self.cfg.vocab_size)
        self._detokenize = detokenize or _byte_detokenize
        # jitted programs, shared by the batched and streaming paths
        self._prefill = jax.jit(partial(prefill, self.cfg))
        self._decode_step = jax.jit(partial(decode_step, self.cfg))
        self._key = jax.random.PRNGKey(0)
        import threading

        self._key_lock = threading.Lock()  # batch flushes run on executor threads
        # deploy-time batch size overrides the @serve.batch default
        setattr(self, "__serve_batch_size__generate_batch", max_batch_size)

    # ------------------------------------------------------------ batched

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.02)
    async def _generate_batch(self, prompts: List[List[int]]) -> List[List[int]]:
        """Continuous batching: concurrent requests run one decode program.
        The jax work runs on an executor thread — blocking the replica's
        event loop would stall health checks and stream pulls."""
        import asyncio

        return await asyncio.get_running_loop().run_in_executor(
            None, self._generate_batch_sync, prompts)

    def _generate_batch_sync(self, prompts: List[List[int]]) -> List[List[int]]:
        """Group prompts by exact length and run one decode program per
        group. Padding mixed lengths into one program would let real tokens
        attend to pad positions (the causal cache mask has no pad masking),
        silently degrading shorter prompts; grouping keeps every program
        exact while still batching the common same-shape case."""
        by_len: Dict[int, List[int]] = {}
        for i, p in enumerate(prompts):
            by_len.setdefault(len(p), []).append(i)
        outs: List[List[int]] = [[] for _ in prompts]
        for _length, indices in by_len.items():
            group = [prompts[i] for i in indices]
            for i, out in zip(indices, self._generate_group(group)):
                outs[i] = out
        return outs

    def _generate_group(self, prompts: List[List[int]]) -> List[List[int]]:
        """One batched decode program over same-length prompts."""
        jnp = self._jnp
        batch = len(prompts)
        length = len(prompts[0])
        tokens = jnp.asarray(prompts, dtype=jnp.int32)
        caches = init_caches(self.cfg, batch, length + self.max_new_tokens)
        logits, caches = self._prefill(self.params, tokens, caches)
        outs: List[List[int]] = [[] for _ in range(batch)]
        for _ in range(self.max_new_tokens):
            with self._key_lock:
                self._key, sub = self._jax.random.split(self._key)
            tok = sample_token(logits, sub, self.temperature)
            for i, t in enumerate(tok.tolist()):
                outs[i].append(int(t))
            logits, caches = self._decode_step(
                self.params, tok[:, None].astype(jnp.int32), caches)
        return outs

    # ---------------------------------------------------------- streaming

    def _generate_stream(self, prompt_ids: List[int]):
        """Yield decoded text one token at a time (single-sequence decode:
        a stream holds its own KV cache for its whole lifetime)."""
        jnp = self._jnp
        tokens = jnp.asarray([prompt_ids], dtype=jnp.int32)
        caches = init_caches(self.cfg, 1, len(prompt_ids) + self.max_new_tokens)
        logits, caches = self._prefill(self.params, tokens, caches)
        key = self._jax.random.PRNGKey(len(prompt_ids))
        for _ in range(self.max_new_tokens):
            key, sub = self._jax.random.split(key)
            tok = sample_token(logits, sub, self.temperature)
            yield self._detokenize([int(tok[0])])
            logits, caches = self._decode_step(
                self.params, tok[:, None].astype(jnp.int32), caches)

    # ------------------------------------------------------------ entry

    async def __call__(self, request: Optional[Dict[str, Any]] = None):
        request = request or {}
        if isinstance(request, str):
            request = {"prompt": request}
        prompt = request.get("prompt", "")
        ids = self._tokenize(prompt)
        if not ids:
            raise ValueError("prompt must be non-empty")
        if request.get("stream"):
            return self._generate_stream(ids)
        out_ids = await self._generate_batch(ids)
        return {"prompt": prompt, "text": self._detokenize(out_ids),
                "num_tokens": len(out_ids)}

    def check_health(self) -> bool:
        return self.params is not None


def build_app(preset: str = "llama_debug", num_replicas: int = 1,
              max_new_tokens: int = 16, temperature: float = 0.0,
              **kwargs) -> "serve.Application":
    """`serve.run(build_app(...), route_prefix="/llm")` — the deployable
    LLM decode application."""
    dep = LLMServer.options(num_replicas=num_replicas)
    return dep.bind(preset=preset, max_new_tokens=max_new_tokens,
                    temperature=temperature, **kwargs)
