"""Synchronous client for the serve RPC ingress (no cluster membership,
no HTTP stack — just the framework's length-prefixed frames).

≈ the generated gRPC stub of the reference's gRPC ingress; see
`_private/rpc_ingress.py` for the server."""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Dict, Iterator, Optional


class ServeRpcClient:
    def __init__(self, address: str, request_timeout_s: float = 120.0):
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever,
                                        name="serve-rpc-client", daemon=True)
        self._thread.start()

        async def mk():
            from ray_tpu._private.rpc import RpcClient

            return RpcClient(address, request_timeout_s=request_timeout_s)

        self._client = self._call_async(mk())

    def _call_async(self, coro, timeout=None):
        return asyncio.run_coroutine_threadsafe(
            coro, self._loop).result(timeout)

    def invoke(self, app: str, payload: Any = None, *,
               method: Optional[str] = None,
               multiplexed_model_id: str = "",
               args: Optional[list] = None,
               kwargs: Optional[Dict[str, Any]] = None) -> Any:
        reply = self._call_async(self._client.call("invoke", {
            "app": app, "payload": payload, "method": method,
            "multiplexed_model_id": multiplexed_model_id,
            "args": args, "kwargs": kwargs,
        }))
        if "stream" in reply:
            raise ValueError(
                "endpoint streams; use invoke_stream() instead")
        return reply["result"]

    def invoke_stream(self, app: str, payload: Any = None, **kw
                      ) -> Iterator[Any]:
        reply = self._call_async(self._client.call("invoke", {
            "app": app, "payload": payload,
            "method": kw.get("method"),
            "multiplexed_model_id": kw.get("multiplexed_model_id", ""),
            "args": kw.get("args"), "kwargs": kw.get("kwargs"),
        }))
        if "stream" not in reply:
            yield reply["result"]
            return
        sid = reply["stream"]
        while True:
            chunk = self._call_async(
                self._client.call("stream_next", {"stream": sid}))
            for item in chunk.get("items", ()):
                yield item
            if chunk.get("error"):
                raise RuntimeError(chunk["error"])
            if chunk.get("done"):
                return

    def close(self) -> None:
        try:
            self._call_async(self._client.close())
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=2)
