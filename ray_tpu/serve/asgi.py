"""ASGI ingress adapter — serve any ASGI app (FastAPI, Starlette, raw
ASGI callables) as a deployment.

Analog of `serve.ingress` (`python/ray/serve/api.py:172`) plus the
proxy's ASGI/websocket bridging (`serve/_private/proxy.py:431`):

    app = FastAPI()            # any ASGI3 app object

    @serve.deployment
    @serve.ingress(app)
    class MyService:
        ...                    # regular deployment class; `app` routes
                               # can call its methods via `self`

HTTP requests reaching the proxy for this deployment are translated to
ASGI scope/receive/send; response headers and body chunks stream back
over the native generator transport, so an ASGI streaming response
(chunked transfer, SSE) streams end-to-end. Websocket scopes run the
same app with a bidirectional bridge: outbound ASGI events ride a
streaming generator to the proxy, inbound client frames are fed by
per-message actor calls into the session's receive queue.

FastAPI itself is optional — the adapter speaks the ASGI3 protocol, and
the tests exercise it with dependency-free ASGI apps.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict


_CLOSED = object()  # websocket-session tombstone (see __serve_ws_feed__)


def _encode_scope(scope: Dict[str, Any]) -> Dict[str, Any]:
    """Wire scope (str values, picklable) -> ASGI-spec scope: headers,
    query_string, and raw_path must be bytes (Starlette/FastAPI decode
    them)."""
    scope = dict(scope)
    scope["headers"] = [(k.encode(), v.encode())
                        for k, v in scope.get("headers", [])]
    qs = scope.get("query_string", b"")
    if isinstance(qs, str):
        scope["query_string"] = qs.encode()
    rp = scope.get("raw_path", b"")
    if isinstance(rp, str):
        scope["raw_path"] = rp.encode()
    return scope


def ingress(asgi_app: Any):
    """Class decorator binding *asgi_app* as the deployment's HTTP
    surface (apply UNDER @serve.deployment, reference api.py:172)."""

    def decorator(cls):
        class ASGIIngress(cls):
            # static marker the serve controller publishes with the route
            # so the proxy dispatches ASGI-style without probing user code
            __serve_is_asgi__ = True

            def _ws_sessions(self) -> Dict[str, asyncio.Queue]:
                if not hasattr(self, "__ws_sessions__"):
                    self.__ws_sessions__ = {}
                return self.__ws_sessions__

            async def __serve_asgi__(self, scope: Dict[str, Any],
                                     body: bytes):
                """HTTP: async generator yielding the response-start
                event first, then body chunks (streams incrementally when
                the app streams)."""
                scope = _encode_scope(scope)
                sent_request = False

                async def receive():
                    nonlocal sent_request
                    if not sent_request:
                        sent_request = True
                        return {"type": "http.request",
                                "body": body or b"", "more_body": False}
                    # Starlette's listen_for_disconnect awaits a second
                    # receive() WHILE streaming; returning http.disconnect
                    # here would abort every StreamingResponse. Block
                    # until the request task is torn down instead.
                    await asyncio.Event().wait()

                queue: asyncio.Queue = asyncio.Queue()

                async def send(event):
                    await queue.put(event)

                async def run_app():
                    try:
                        await asgi_app(scope, receive, send)
                    except Exception as e:  # surfaces as a 500 downstream
                        await queue.put({"type": "__app_error__",
                                         "error": f"{type(e).__name__}: {e}"})
                    finally:
                        await queue.put({"type": "__app_done__"})

                task = asyncio.ensure_future(run_app())
                try:
                    started = False
                    while True:
                        event = await queue.get()
                        etype = event["type"]
                        if etype == "http.response.start":
                            started = True
                            yield {"status": event["status"],
                                   "headers": [
                                       (k.decode(), v.decode())
                                       for k, v in event.get("headers", [])]}
                        elif etype == "http.response.body":
                            chunk = event.get("body", b"")
                            if chunk:
                                yield chunk
                            if not event.get("more_body", False):
                                return
                        elif etype == "__app_error__":
                            if not started:
                                yield {"status": 500,
                                       "headers": [("content-type",
                                                    "text/plain")]}
                            yield event["error"].encode()
                            return
                        elif etype == "__app_done__":
                            if not started:
                                yield {"status": 500,
                                       "headers": [("content-type",
                                                    "text/plain")]}
                                yield b"ASGI app sent no response"
                            return
                finally:
                    task.cancel()

            async def __serve_ws__(self, session_id: str,
                                   scope: Dict[str, Any]):
                """Websocket: async generator of outbound ASGI events;
                inbound frames arrive via __serve_ws_feed__."""
                scope = _encode_scope(scope)
                scope["type"] = "websocket"
                inbound = self._ws_sessions().setdefault(
                    session_id, asyncio.Queue())
                await inbound.put({"type": "websocket.connect"})
                outbound: asyncio.Queue = asyncio.Queue()

                async def receive():
                    return await inbound.get()

                async def send(event):
                    await outbound.put(event)

                async def run_app():
                    try:
                        await asgi_app(scope, receive, send)
                    except Exception as e:
                        await outbound.put({"type": "websocket.close",
                                            "code": 1011,
                                            "reason": f"{e}"})
                    finally:
                        await outbound.put({"type": "__app_done__"})

                task = asyncio.ensure_future(run_app())
                try:
                    while True:
                        event = await outbound.get()
                        if event["type"] == "__app_done__":
                            return
                        yield event
                        if event["type"] == "websocket.close":
                            return
                finally:
                    task.cancel()
                    # tombstone, not pop: the proxy's final disconnect
                    # feed must not setdefault() a fresh queue that then
                    # leaks (one per closed websocket on a long-lived
                    # replica)
                    self._ws_sessions()[session_id] = _CLOSED

            async def __serve_ws_feed__(self, session_id: str,
                                        event: Dict[str, Any]) -> bool:
                """Inbound client frame -> the session's receive queue.
                Async so it runs on the actor loop (asyncio.Queue is not
                thread-safe). Returns False when the session is gone."""
                sessions = self._ws_sessions()
                q = sessions.get(session_id)
                if q is _CLOSED:
                    # session over. Only the proxy's FINAL feed (the
                    # disconnect in its finally block) clears the
                    # tombstone — an in-flight data frame racing the
                    # close must not consume it, or the disconnect feed
                    # would setdefault a fresh queue and leak it
                    if event.get("type") == "websocket.disconnect":
                        sessions.pop(session_id, None)
                    return False
                if q is None:
                    # a client frame can race __serve_ws__'s queue
                    # registration (the proxy feeds per-message while the
                    # streaming call is still being scheduled) — early
                    # frames must buffer, not drop
                    q = sessions.setdefault(session_id, asyncio.Queue())
                q.put_nowait(event)
                return True

        ASGIIngress.__name__ = cls.__name__
        ASGIIngress.__qualname__ = cls.__qualname__
        ASGIIngress.__module__ = cls.__module__
        return ASGIIngress

    return decorator
