"""Speculative decoding for the continuous-batching scheduler (ISSUE 18).

A small DRAFTER model proposes k tokens per active slot; the target model
scores all k (plus the bonus position) in ONE fixed-shape
``paged_verify_step`` call over the slots axis — a k+1-token
prefill-shaped program, the third (and only third) compiled program next
to the scheduler's prefill/decode pair. Acceptance is the exact
algorithm of arXiv:2211.17192: accept the longest draft prefix whose
tokens survive the q/p coin flips, resample the first rejection from the
corrected distribution max(q - p, 0), and sample the bonus token from
the target when every draft survives — so the OUTPUT DISTRIBUTION is
identically the target model's, and at temperature 0 the emitted tokens
are bit-exactly the sequential greedy path's.

The drafter owns a contiguous ``SlotKVCache`` arena (its own two jitted
programs) mirroring the scheduler's slot assignment. Its params come
from the shared weights arena (PR-9 ``get_or_publish``); the special
drafter ``"self"`` reuses the target's own device params, in which case
a slot's drafter KV is ADOPTED from the target's paged cache by an
eager gather (no drafter prefill — the prefix-cache TTFT win survives),
otherwise the drafter prefills the prompt through its own model.
Rejected drafts rewind cursors only — never pages: stale KV past a
cursor is causally masked until overwritten (the arena's standing
update-before-attend invariant).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu._private.metrics import Counter

m_spec_drafted = Counter(
    "ray_tpu_serve_spec_drafted_tokens_total",
    "Draft tokens proposed by the speculative drafter")
m_spec_accepted = Counter(
    "ray_tpu_serve_spec_accepted_tokens_total",
    "Draft tokens accepted by target-model verification")


def _softmax(logits_row, temperature: float) -> np.ndarray:
    x = np.asarray(logits_row, np.float64) / temperature
    x -= x.max()
    p = np.exp(x)
    return p / p.sum()


def accept_sample(draft_tokens: Sequence[int], p_draft, p_target,
                  rng) -> Tuple[int, List[int]]:
    """Exact speculative acceptance (temperature > 0).

    draft_tokens: the k proposed tokens. p_draft: [k, V] drafter
    probabilities (row j is the distribution d_{j+1} was sampled from).
    p_target: [k+1, V] target probabilities (row j scores position j;
    row k is the bonus distribution valid only when every draft is
    accepted). Returns ``(accepted, emitted)`` where emitted is
    ``drafts[:accepted] + [corrected-or-bonus token]`` — always exactly
    one more token than accepted, matching what sequential sampling from
    the target would emit in distribution."""
    k = len(draft_tokens)
    for j in range(k):
        d = int(draft_tokens[j])
        q = float(p_target[j][d])
        p = float(p_draft[j][d])
        if p > 0.0 and rng.uniform() < min(1.0, q / p):
            continue
        resid = np.maximum(np.asarray(p_target[j], np.float64)
                           - np.asarray(p_draft[j], np.float64), 0.0)
        s = resid.sum()
        if s <= 0.0:
            # q == p pointwise (possible up to float round-off): any
            # sample from q is exact
            tok = int(rng.choice(len(resid), p=np.asarray(p_target[j],
                                                          np.float64)
                                 / np.asarray(p_target[j],
                                              np.float64).sum()))
        else:
            tok = int(rng.choice(len(resid), p=resid / s))
        return j, [int(t) for t in draft_tokens[:j]] + [tok]
    pt = np.asarray(p_target[k], np.float64)
    tok = int(rng.choice(len(pt), p=pt / pt.sum()))
    return k, [int(t) for t in draft_tokens] + [tok]


def accept_greedy(draft_tokens: Sequence[int],
                  target_logits) -> Tuple[int, List[int]]:
    """Temperature-0 acceptance: accept the longest prefix where each
    draft equals the target argmax, then emit the target argmax at the
    first divergence (or the bonus argmax after a full accept). This IS
    what the sequential greedy loop emits, token for token — argmax over
    the same logits rows the single-token program would produce."""
    k = len(draft_tokens)
    emitted: List[int] = []
    for j in range(k):
        t = int(np.asarray(target_logits[j]).argmax())
        if t != int(draft_tokens[j]):
            return j, emitted + [t]
        emitted.append(t)
    bonus = int(np.asarray(target_logits[k]).argmax())
    return k, emitted + [bonus]


class Drafter:
    """The drafter's model state: params + a contiguous ``SlotKVCache``
    arena sharing the scheduler's slot numbering, plus its own two
    jitted programs (one prefill chunk shape, one [slots] decode shape).
    All methods run on the scheduler thread."""

    def __init__(self, cfg, params, *, slots: int, arena_len: int,
                 name: str = "self", shares_target: bool = False):
        import jax

        from ray_tpu.models.decode import (init_slot_caches,
                                           prefill_into_slot,
                                           slot_decode_step)

        self.cfg = cfg
        self.params = params
        self.name = name
        # True iff ``params`` are (a shared copy of) the TARGET's params:
        # only then is the target's paged KV the drafter's own KV and
        # adoption-by-gather is valid
        self.shares_target = shares_target
        self.slots = slots
        self.arena_len = arena_len
        self._jax = jax
        self._prefill = jax.jit(partial(prefill_into_slot, cfg),
                                donate_argnums=(4,))
        self._step = jax.jit(partial(slot_decode_step, cfg),
                             donate_argnums=(3,))
        self._caches = init_slot_caches(cfg, slots, arena_len)

    # ------------------------------------------------------------ state

    def lengths(self) -> np.ndarray:
        return np.asarray(self._caches[0].lengths)

    def set_lengths(self, new_lengths) -> None:
        """Host-side cursor rewind after a verify round (rejected drafts'
        KV stays, masked until overwritten). One device buffer PER layer:
        the drafter's step donates its caches and a shared buffer would
        be donated once per layer."""
        import jax.numpy as jnp

        host = np.asarray(new_lengths, np.int32)
        self._caches = [dataclasses.replace(c, lengths=jnp.asarray(host))
                        for c in self._caches]

    def reset_slot(self, slot: int) -> None:
        self._caches = [
            dataclasses.replace(c, lengths=c.lengths.at[slot].set(0))
            for c in self._caches]

    # ----------------------------------------------------- slot priming

    def adopt_from_paged(self, slot: int, target_caches, read_row,
                         length: int, page_tokens: int) -> None:
        """Prime a slot by copying the target's paged KV for positions
        [0, length) into the drafter's contiguous row — valid ONLY when
        the drafter shares the target's params (then target KV == the KV
        this drafter would have computed, bit for bit). Eager gather, no
        program compilation."""
        if not self.shares_target:
            raise RuntimeError(
                "adopt_from_paged requires a drafter sharing the target's "
                "params (drafter='self')")
        import jax.numpy as jnp

        idx = jnp.asarray(np.asarray(read_row, np.int32))
        out = []
        for dc, tc in zip(self._caches, target_caches):
            H, D = tc.k.shape[2:]
            vk = tc.k[idx].reshape(-1, H, D)[:length]
            vv = tc.v[idx].reshape(-1, H, D)[:length]
            out.append(dataclasses.replace(
                dc,
                k=dc.k.at[slot, :length].set(vk.astype(dc.k.dtype)),
                v=dc.v.at[slot, :length].set(vv.astype(dc.v.dtype)),
                lengths=dc.lengths.at[slot].set(np.int32(length))))
        self._caches = out

    def prefill_prompt(self, slot: int, tokens: Sequence[int],
                       chunk: int) -> None:
        """Prime a slot by running the prompt through the DRAFTER model
        in fixed-width chunks (a distinct drafter cannot adopt the
        target's KV — different model, different cache). One compiled
        shape: the scheduler always passes its own prefill_chunk."""
        import jax.numpy as jnp

        self.reset_slot(slot)
        rest = list(tokens)
        while rest:
            piece = rest[:chunk]
            rest = rest[chunk:]
            real = len(piece)
            padded = piece + [0] * (chunk - real)
            _, self._caches = self._prefill(
                self.params, jnp.asarray([padded], jnp.int32),
                np.int32(real), np.int32(slot), self._caches)

    # ------------------------------------------------------------- step

    def step(self, tokens: np.ndarray, active: np.ndarray):
        """One batched drafter decode step over all slots. Returns the
        [slots, vocab] logits as numpy (the host samples drafts)."""
        import jax.numpy as jnp

        logits, self._caches = self._step(
            self.params, jnp.asarray(tokens), jnp.asarray(active),
            self._caches)
        return np.asarray(logits)

    def compiled_programs(self) -> int:
        return int(self._prefill._cache_size() + self._step._cache_size())
