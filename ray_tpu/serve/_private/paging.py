"""Paged KV arena allocator + prefix/radix cache (ISSUE 13).

Host-side bookkeeping for the paged slot arena in ``models.decode``:

  * ``PageArena`` — a free-list allocator over the fixed pool of
    ``page_tokens``-sized KV pages. Page 0 is RESERVED as the garbage
    page (unallocated/shared write-table entries redirect there), so a
    pool of N pages holds N-1 sequences' worth of allocatable pages.
    Allocation and release are O(1) list ops on the scheduler thread —
    no locks, no RPCs, nothing on the device.

  * ``RadixCache`` — a radix tree over PROMPT token prefixes whose nodes
    reference refcounted read-only pages. Admitting a request whose
    prompt shares a cached prefix becomes a page-table splice + cursor
    jump (the PR-9 shared-weights idiom applied to KV) instead of a
    re-prefill. Every node covers a whole number of pages, so a partial
    match SPLITS an edge cleanly at a page boundary. Eviction is LRU
    over refcount-0 LEAVES under arena pressure (an interior node is
    unreachable-from-root once evicted, so leaves go first and parents
    become evictable as their subtrees drain).

Both are single-thread structures: the continuous scheduler owns them and
touches them only from its own loop thread (admission validation in
``submit`` is pure arithmetic and reads no allocator state).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private import flight
from ray_tpu._private.metrics import Counter, Gauge
from ray_tpu.serve._private.affinity import CHAIN_SEED, chain_hashes

F_PREFIX_HIT = flight.intern("serve.prefix_hit")
F_PAGE_ALLOC = flight.intern("serve.page_alloc")
F_EVICT = flight.intern("serve.evict")

m_prefix_hits = Counter(
    "ray_tpu_serve_prefix_hits_total",
    "Admissions that spliced a cached KV prefix instead of re-prefilling")
m_prefix_misses = Counter(
    "ray_tpu_serve_prefix_misses_total",
    "Admissions that found no cached prefix (cold prefill)")
m_pages_allocated = Counter(
    "ray_tpu_serve_kv_pages_allocated_total",
    "KV pages handed out by the paged arena")
m_pages_freed = Counter(
    "ray_tpu_serve_kv_pages_freed_total",
    "KV pages returned to the paged arena free list")
m_pages_in_use = Gauge(
    "ray_tpu_serve_kv_pages_in_use",
    "KV pages currently allocated (slot-owned + prefix-cache resident)")

GARBAGE_PAGE = 0


class OutOfPagesError(RuntimeError):
    """The arena has no free page and nothing evictable remains."""


class PageArena:
    """Free-list allocator over the paged KV pool. Page ids are indices
    into the device-side ``PagedKVCache`` pools; page 0 never leaves the
    allocator (it is the shared garbage page)."""

    def __init__(self, num_pages: int, page_tokens: int):
        if page_tokens < 1:
            # the PR-8/PR-9 falsy-zero lesson: an explicit 0 must raise
            # here, never silently become some default upstream
            raise ValueError(
                f"page_tokens must be >= 1, got {page_tokens}")
        if num_pages < 2:
            raise ValueError(
                f"kv arena needs >= 2 pages (page 0 is reserved), "
                f"got {num_pages}")
        self.num_pages = num_pages
        self.page_tokens = page_tokens
        # LIFO free list: recently-freed pages are re-used first (their
        # content is dead by construction — cursors never read past a
        # slot's own writes)
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        # outstanding page ids: a double-free or foreign id is the one
        # bookkeeping slip that would hand the same physical page to two
        # slots (silent cross-sequence KV contamination) — fail LOUDLY
        # at the free site instead
        self._outstanding: set = set()
        self._allocated_total = 0
        self._freed_total = 0
        self._peak_in_use = 0

    @property
    def usable_pages(self) -> int:
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.usable_pages - len(self._free)

    def alloc(self, n: int) -> List[int]:
        """Allocate ``n`` pages or raise ``OutOfPagesError`` allocating
        NONE (no partial grants — the caller retries after eviction)."""
        if n <= 0:
            return []
        if len(self._free) < n:
            raise OutOfPagesError(
                f"kv arena out of pages: need {n}, "
                f"{len(self._free)} free of {self.usable_pages}")
        pages = [self._free.pop() for _ in range(n)]
        self._outstanding.update(pages)
        self._allocated_total += n
        self._peak_in_use = max(self._peak_in_use, self.pages_in_use)
        m_pages_allocated.inc(n)
        m_pages_in_use.set(float(self.pages_in_use))
        flight.instant(F_PAGE_ALLOC, n)
        return pages

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if p == GARBAGE_PAGE:
                raise ValueError("page 0 is reserved and never allocated")
            if p not in self._outstanding:
                raise ValueError(
                    f"page {p} freed while not allocated (double-free or "
                    f"foreign id) — would alias two sequences' KV")
            self._outstanding.discard(p)
            self._free.append(p)
        if pages:
            self._freed_total += len(pages)
            m_pages_freed.inc(len(pages))
            m_pages_in_use.set(float(self.pages_in_use))

    def stats(self) -> Dict[str, int]:
        return {
            "num_pages": self.num_pages,
            "usable_pages": self.usable_pages,
            "pages_in_use": self.pages_in_use,
            "pages_free": len(self._free),
            "pages_allocated_total": self._allocated_total,
            "pages_freed_total": self._freed_total,
            "peak_pages_in_use": self._peak_in_use,
        }


class _RadixNode:
    __slots__ = ("tokens", "pages", "children", "parent", "refs",
                 "last_used", "hashes")

    def __init__(self, tokens: Tuple[int, ...], pages: List[int],
                 parent: Optional["_RadixNode"],
                 hashes: Optional[List[int]] = None):
        self.tokens = tokens          # this EDGE's token span
        self.pages = pages            # pages backing exactly that span
        self.children: Dict[int, "_RadixNode"] = {}  # first-token -> child
        self.parent = parent
        self.refs = 0                 # live slots holding this node
        self.last_used = 0.0
        # per-page CHAIN hashes (affinity digest): hashes[i] commits to
        # the whole root path through this node's page i. Parallel to
        # ``pages``; splits slice it, never recompute it
        self.hashes: List[int] = hashes if hashes is not None else []

    def chain_end(self) -> int:
        """The chain value new children extend from."""
        return self.hashes[-1] if self.hashes else CHAIN_SEED


class RadixCache:
    """Radix tree over prompt prefixes; nodes own read-only pages.

    Every edge span is a whole number of pages (``page_tokens`` each), so
    matching, splitting and eviction all happen at page boundaries and a
    node's ``pages`` list is exactly parallel to its token span.

    Refcounting: ``match``/``insert`` return the deepest node on the path
    with ``refs`` already incremented; the caller MUST ``release`` it when
    the sequence retires. A node is evictable iff it is a leaf with
    refs == 0 (an ancestor of a referenced node has children, hence is
    not a leaf, hence is safe).
    """

    def __init__(self, arena: PageArena, clock=time.monotonic):
        self.arena = arena
        self.page_tokens = arena.page_tokens
        self._root = _RadixNode((), [], None)
        self._clock = clock
        self._hits = 0
        self._misses = 0
        self._evicted_pages = 0
        # affinity digest: count per chain hash (counts, not a set — two
        # sibling subtrees can't share a chain value, but a hash that
        # reappears after an evict/re-insert race must not flicker) and a
        # version stamp the long-poll channel keys on
        self._digest: Dict[int, int] = {}
        self._digest_version = 0

    # ------------------------------------------------------------ match

    def match(self, tokens: List[int]) -> Tuple[List[int], int,
                                                Optional[_RadixNode]]:
        """Longest cached page-aligned prefix of ``tokens``.

        Returns (pages, matched_len, node): the shared pages covering
        ``tokens[:matched_len]`` and the deepest node on the path
        (ref-counted — caller releases it at retire). A partial edge
        match splits the edge at the page boundary so the matched part
        becomes its own node. (None, for a zero-length match.)

        Match is metrics-free: the CALLER decides whether the match is
        actually spliced (it may clamp it away entirely) and records the
        hit/miss via ``note_hit``/``note_miss`` — so ``prefix_hits``
        counts avoided prefills, never discarded matches.
        """
        now = self._clock()
        node = self._root
        pages: List[int] = []
        matched = 0
        rest = tokens
        while rest:
            child, n = self._advance(node, rest, now)
            if n == 0:
                break
            pages.extend(child.pages)
            matched += n
            rest = rest[n:]
            node = child
        if node is self._root:
            return [], 0, None
        node.refs += 1
        return pages, matched, node

    def note_hit(self, matched_tokens: int) -> None:
        """Record an admission that spliced a cached prefix (call AFTER
        any clamping — only an avoided prefill counts)."""
        self._hits += 1
        m_prefix_hits.inc()
        flight.instant(F_PREFIX_HIT, matched_tokens)

    def note_miss(self) -> None:
        self._misses += 1
        m_prefix_misses.inc()

    def _advance(self, node: _RadixNode, rest: List[int], now: float
                 ) -> Tuple[Optional[_RadixNode], int]:
        """One descend step shared by ``match`` and ``insert``: find the
        child edge for ``rest``, page-align the shared length, split the
        edge at that boundary and stamp its LRU time. Returns (child, n):
        n == 0 means no child or a collision with no full shared page —
        in the latter case the node's LRU stamp is deliberately NOT
        refreshed (a stream of near-miss probes must not keep a never-hit
        node resident while genuinely reused nodes get evicted)."""
        child = node.children.get(rest[0])
        if child is None:
            return None, 0
        span = child.tokens
        n = 0
        limit = min(len(span), len(rest))
        while n < limit and span[n] == rest[n]:
            n += 1
        n = (n // self.page_tokens) * self.page_tokens
        if n == 0:
            return child, 0
        child.last_used = now
        if n < len(span):
            child = self._split(child, n)
            child.last_used = now
        return child, n

    def _split(self, node: _RadixNode, at: int) -> _RadixNode:
        """Split ``node``'s edge after ``at`` tokens (a page multiple);
        returns the new upper node. The lower half keeps the children and
        the refs (live slots reference the FULL path content)."""
        T = self.page_tokens
        upper = _RadixNode(tuple(node.tokens[:at]),
                           node.pages[: at // T], node.parent,
                           hashes=node.hashes[: at // T])
        upper.last_used = node.last_used
        node.parent.children[upper.tokens[0]] = upper
        lower_tokens = tuple(node.tokens[at:])
        node.tokens = lower_tokens
        node.pages = node.pages[at // T:]
        # chain hashes commit to the whole root path, so redistributing
        # them across the split needs no recompute — the digest set is
        # unchanged by a split
        node.hashes = node.hashes[at // T:]
        node.parent = upper
        upper.children[lower_tokens[0]] = node
        return upper

    # ----------------------------------------------------------- insert

    def insert(self, tokens: List[int], pages: List[int]
               ) -> Tuple[List[int], _RadixNode]:
        """Offer the pages backing ``tokens`` (page-aligned length) to the
        cache. Spans already cached keep their EXISTING pages; the novel
        suffix's pages are adopted by new nodes.

        Returns (duplicate_pages, node): the caller-owned pages NOT
        adopted (already covered — caller frees or keeps them) and the
        deepest node of the inserted path, ref-counted for the caller.
        """
        T = self.page_tokens
        if len(tokens) % T != 0 or len(tokens) // T != len(pages):
            raise ValueError(
                f"insert span must be page-aligned: {len(tokens)} tokens, "
                f"{len(pages)} pages, page_tokens={T}")
        now = self._clock()
        node = self._root
        rest = list(tokens)
        rest_pages = list(pages)
        duplicates: List[int] = []
        while rest:
            child, n = self._advance(node, rest, now)
            if child is None:
                new = _RadixNode(
                    tuple(rest), rest_pages, node,
                    hashes=chain_hashes(rest, T, seed=node.chain_end()))
                new.last_used = now
                node.children[rest[0]] = new
                self._digest_add(new.hashes)
                node = new
                rest, rest_pages = [], []
                break
            if n == 0:
                # same first token but no full shared page — token-level
                # divergence inside page 1 of the edge. The cache keeps
                # the incumbent; the new span is not representable at
                # page granularity alongside it
                duplicates.extend(rest_pages)
                rest, rest_pages = [], []
                break
            duplicates.extend(rest_pages[: n // T])
            rest = rest[n:]
            rest_pages = rest_pages[n // T:]
            node = child
        duplicates.extend(rest_pages)
        if node is self._root:
            return duplicates, None
        node.refs += 1
        return duplicates, node

    def release(self, node: Optional[_RadixNode]) -> None:
        if node is not None:
            if node.refs <= 0:
                raise RuntimeError("radix node released more times than "
                                   "matched")
            node.refs -= 1

    # ---------------------------------------------------------- evict

    def evict(self, need_pages: int) -> int:
        """Free LRU refcount-0 leaves until ``need_pages`` pages have been
        returned to the arena (or nothing evictable remains). Returns the
        number of pages actually freed.

        One tree scan collects ALL evictable leaves for the round (LRU
        order); only a cascade — a parent becoming a leaf as its subtree
        drains — triggers another scan, so the cost is O(nodes x depth)
        worst case instead of O(nodes x victims)."""
        freed = 0
        while freed < need_pages:
            candidates = []
            stack = [self._root]
            while stack:
                n = stack.pop()
                for c in n.children.values():
                    if not c.children and c.refs == 0:
                        candidates.append(c)
                    else:
                        stack.append(c)
            if not candidates:
                break
            candidates.sort(key=lambda c: c.last_used)
            for victim in candidates:
                if freed >= need_pages:
                    break
                victim.parent.children.pop(victim.tokens[0])
                self._digest_remove(victim.hashes)
                self.arena.free(victim.pages)
                freed += len(victim.pages)
                self._evicted_pages += len(victim.pages)
                flight.instant(F_EVICT, len(victim.pages))
        return freed

    def clear(self) -> int:
        """Drop every unreferenced node (shutdown / tests); still-referenced
        nodes survive. Returns pages freed."""
        return self.evict(1 << 30)

    # --------------------------------------------------------- digest

    def _digest_add(self, hashes: List[int]) -> None:
        for h in hashes:
            self._digest[h] = self._digest.get(h, 0) + 1
        if hashes:
            self._digest_version += 1

    def _digest_remove(self, hashes: List[int]) -> None:
        for h in hashes:
            n = self._digest.get(h, 0) - 1
            if n <= 0:
                self._digest.pop(h, None)
            else:
                self._digest[h] = n
        if hashes:
            self._digest_version += 1

    def digest(self) -> Dict:
        """Affinity digest snapshot (ISSUE 18): every page-boundary chain
        hash currently resident, plus a version stamp. Maintained
        incrementally by insert/evict/split — this is a dict-keys copy,
        safe to call from the stats path at poll rates. Callers that ship
        it off-process add tokenizer metadata (vocab_size / tok) so the
        router can hash prompts the same way."""
        return {
            "page_tokens": self.page_tokens,
            "hashes": list(self._digest.keys()),
            "version": self._digest_version,
        }

    # ---------------------------------------------------------- stats

    def _walk_totals(self) -> Tuple[int, int, int]:
        """(nodes, resident_pages, active_refs) in ONE tree traversal —
        stats() is polled in tight loops by chaos baselines and benches."""
        nodes, pages, refs = -1, 0, 0  # -1: exclude the root sentinel
        stack = [self._root]
        while stack:
            n = stack.pop()
            nodes += 1
            pages += len(n.pages)
            refs += n.refs
            stack.extend(n.children.values())
        return nodes, pages, refs

    def resident_pages(self) -> int:
        return self._walk_totals()[1]

    def active_refs(self) -> int:
        return self._walk_totals()[2]

    def node_count(self) -> int:
        return self._walk_totals()[0]

    def stats(self) -> Dict[str, int]:
        hits, misses = self._hits, self._misses
        nodes, pages, refs = self._walk_totals()
        return {
            "prefix_hits": hits,
            "prefix_misses": misses,
            "prefix_hit_rate": round(hits / max(hits + misses, 1), 4),
            "radix_nodes": nodes,
            "radix_resident_pages": pages,
            "radix_active_refs": refs,
            "evicted_pages_total": self._evicted_pages,
        }
