"""Binary RPC ingress — the second (non-HTTP) ingress protocol.

Role-parity with the reference's gRPC ingress (`python/ray/serve/
grpc_util.py` + proxy gRPC service): a length-prefixed binary protocol for
low-overhead programmatic clients, speaking the framework's native RPC
framing (`_private/rpc.py`) instead of gRPC — the control plane's stance
(no proto toolchain; this image ships no grpcio) applied to the ingress.

Server: an actor that routes `invoke` frames to applications by name and
streams chunked responses for generator endpoints.

Client:
    from ray_tpu.serve.rpc_ingress_client import ServeRpcClient
    c = ServeRpcClient("host:port")
    c.invoke("default", {"prompt": "hi"})        # -> result
    for tok in c.invoke_stream("llm", {...}):    # -> chunks
        ...
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict

import ray_tpu

logger = logging.getLogger(__name__)


class RpcIngressActor:
    """Async actor hosting an RpcServer; `invoke` routes to app handles
    through the same pow-2 router as every other caller."""

    STREAM_IDLE_TTL_S = 60.0

    def __init__(self, controller, port: int = 0):
        self._controller = controller
        self._port = port
        self._server = None
        self._handles: Dict[str, Any] = {}
        # ingress stream key (uuid) -> (replica_handle, replica_sid,
        # last_pull_ts): replica sids are per-replica counters and collide
        # across replicas, so the ingress mints its own ids
        self._streams: Dict[str, list] = {}
        self._started = asyncio.Event()
        self._janitor = None

    async def ready(self) -> int:
        if self._server is None:
            from ray_tpu._private.rpc import RpcServer

            self._server = RpcServer(host="0.0.0.0", port=self._port)
            self._server.register("invoke", self._invoke)
            self._server.register("stream_next", self._stream_next)
            addr = await self._server.start()
            self._port = addr[1]
            self._janitor = asyncio.ensure_future(self._janitor_loop())
            self._started.set()
            logger.info("serve rpc ingress on :%d", self._port)
        else:
            await self._started.wait()
        return self._port

    async def _janitor_loop(self):
        """Reap abandoned streams (the replica reaps its side after its
        own idle TTL; the ingress must not leak its mapping) and drop
        cached app handles whose route target changed (redeploys)."""
        import time

        while True:
            await asyncio.sleep(5.0)
            now = time.monotonic()
            for key, rec in list(self._streams.items()):
                if now - rec[2] > self.STREAM_IDLE_TTL_S:
                    self._streams.pop(key, None)
            try:
                routes = await self._controller.get_routes.remote()
            except Exception:
                continue
            targets = {}
            for dest in routes.values():
                app_name, dep = dest.split("/", 1)
                targets[app_name] = dep
            for app, h in list(self._handles.items()):
                if targets.get(app) != h._deployment:
                    self._handles.pop(app, None)
                    # retire the evicted handle's router, or its long-poll
                    # thread keeps polling the dead deployment forever
                    if h._router is not None:
                        try:
                            h._router.stop()
                        except Exception:
                            pass

    async def _handle_for(self, app: str):
        h = self._handles.get(app)
        if h is None:
            routes = await self._controller.get_routes.remote()
            target = None
            for dest in routes.values():
                app_name, dep = dest.split("/", 1)
                if app_name == app:
                    target = (app_name, dep)
                    break
            if target is None:
                raise ValueError(f"no application named {app!r}")
            from ray_tpu.serve.handle import DeploymentHandle

            h = DeploymentHandle(target[0], target[1], self._controller)
            self._handles[app] = h
        return h

    async def _invoke(self, body: Dict[str, Any]):
        import time
        import uuid

        from ray_tpu.serve.handle import STREAM_MARKER

        h = await self._handle_for(body["app"])
        if body.get("multiplexed_model_id"):
            h = h.options(
                multiplexed_model_id=body["multiplexed_model_id"])
        method = body.get("method") or "__call__"
        # an explicit empty args list means a zero-arg call, not f(None)
        args = (body["args"] if body.get("args") is not None
                else [body.get("payload")])
        # router does blocking controller lookups: keep them off this loop
        resp = await asyncio.to_thread(
            lambda: h._call(method, tuple(args), body.get("kwargs") or {}))
        out = await resp
        if isinstance(out, dict) and STREAM_MARKER in out:
            # ingress-unique key: replica sids are per-replica counters
            key = uuid.uuid4().hex[:16]
            self._streams[key] = [resp._replica, out[STREAM_MARKER],
                                  time.monotonic()]
            return {"stream": key}
        return {"result": out}

    async def _stream_next(self, body: Dict[str, Any]):
        import time

        rec = self._streams.get(body["stream"])
        if rec is None:
            return {"items": [], "done": True}
        replica, sid, _ = rec
        rec[2] = time.monotonic()
        chunk = await replica.stream_next.remote(sid)
        if chunk.get("done"):
            self._streams.pop(body["stream"], None)
        return chunk


def start_rpc_ingress(port: int = 0) -> int:
    """Start (or find) the cluster's RPC ingress; returns the bound port.
    ≈ `serve.start(grpc_options=...)` in the reference."""
    from ray_tpu.serve import _get_or_create_controller

    controller = _get_or_create_controller()
    try:
        actor = ray_tpu.get_actor("SERVE_RPC_INGRESS")
    except Exception:
        actor = ray_tpu.remote(RpcIngressActor).options(
            name="SERVE_RPC_INGRESS", lifetime="detached", num_cpus=0.1,
            max_concurrency=256).remote(controller, port)
    return ray_tpu.get(actor.ready.remote())
