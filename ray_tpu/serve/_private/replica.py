"""Replica actor — hosts one copy of the user's deployment callable.

Analog of `ray.serve._private.replica.Replica`
(`python/ray/serve/_private/replica.py`): an async actor
(max_concurrency = max_ongoing_requests, the runtime's fiber-style queue)
that tracks in-flight counts for autoscaling and health. On TPU serving
(v5e decode loops) the callable owns the chips and the jitted decode
program; concurrency>1 lets continuous batching aggregate requests via
`serve.batch`.
"""

from __future__ import annotations

import asyncio
import inspect
import time
from typing import Any, Dict, Optional


class ReplicaActor:
    def __init__(self, app_name: str, deployment_name: str,
                 callable_factory, init_args, init_kwargs):
        self._app = app_name
        self._deployment = deployment_name
        user = callable_factory()
        if inspect.isclass(user):
            self._callable = user(*init_args, **(init_kwargs or {}))
            self._is_function = False
        else:
            self._callable = user
            self._is_function = True
        self._ongoing = 0
        self._total = 0
        self._started = time.time()

    async def handle_request(self, method_name: str, args, kwargs) -> Any:
        self._ongoing += 1
        self._total += 1
        try:
            if self._is_function:
                fn = self._callable
            else:
                fn = getattr(self._callable, method_name or "__call__")
            out = fn(*args, **(kwargs or {}))
            if inspect.isawaitable(out):
                out = await out
            return out
        finally:
            self._ongoing -= 1

    async def reconfigure(self, user_config: Any) -> None:
        if hasattr(self._callable, "reconfigure"):
            out = self._callable.reconfigure(user_config)
            if inspect.isawaitable(out):
                await out

    async def stats(self) -> Dict[str, Any]:
        return {"ongoing": self._ongoing, "total": self._total,
                "uptime_s": time.time() - self._started}

    async def check_health(self) -> bool:
        if hasattr(self._callable, "check_health"):
            out = self._callable.check_health()
            if inspect.isawaitable(out):
                out = await out
            return bool(out) if out is not None else True
        return True

    async def prepare_for_shutdown(self) -> None:
        # drain: wait for in-flight requests
        deadline = time.monotonic() + 10
        while self._ongoing > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
