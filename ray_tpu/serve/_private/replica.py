"""Replica actor — hosts one copy of the user's deployment callable.

Analog of `ray.serve._private.replica.Replica`
(`python/ray/serve/_private/replica.py`): an async actor
(max_concurrency = max_ongoing_requests, the runtime's fiber-style queue)
that tracks in-flight counts for autoscaling and health. On TPU serving
(v5e decode loops) the callable owns the chips and the jitted decode
program; concurrency>1 lets continuous batching aggregate requests via
`serve.batch`.
"""

from __future__ import annotations

import asyncio
import inspect
import time
from typing import Any, Dict, Optional


class _Stream:
    """One in-flight streaming response: the source generator, the chunk
    buffer, and consumer-liveness bookkeeping."""

    __slots__ = ("gen", "queue", "last_pull", "cancelled")

    def __init__(self, gen):
        self.gen = gen
        self.queue: asyncio.Queue = asyncio.Queue()
        self.last_pull = time.monotonic()
        self.cancelled = False

    async def close(self) -> None:
        """Stop the pump and release the generator."""
        self.cancelled = True
        try:
            if inspect.isasyncgen(self.gen):
                await self.gen.aclose()
            else:
                self.gen.close()
        except Exception:
            # a sync generator mid-__next__ on the pump thread raises
            # "generator already executing"; the cancelled flag stops the
            # pump at its next item instead
            pass


class ReplicaActor:
    def __init__(self, app_name: str, deployment_name: str,
                 callable_factory, init_args, init_kwargs):
        self._app = app_name
        self._deployment = deployment_name
        user = callable_factory()
        if inspect.isclass(user):
            self._callable = user(*init_args, **(init_kwargs or {}))
            self._is_function = False
        else:
            self._callable = user
            self._is_function = True
        self._ongoing = 0
        self._total = 0
        self._started = time.time()
        # streaming responses: stream_id -> _Stream
        self._streams: Dict[int, "_Stream"] = {}
        self._next_stream_id = 0
        self._stream_idle_ttl_s = 60.0
        self._stream_reaper_task = None

    def multiplex_info(self) -> Dict[str, Any]:
        """Model ids this replica has loaded (router affinity source)."""
        from ray_tpu.serve.multiplex import loaded_model_ids

        return {"model_ids": loaded_model_ids(self._callable)}

    async def handle_request(self, method_name: str, args, kwargs) -> Any:
        from ray_tpu.serve.multiplex import _set_request_model_id

        mux_token = None
        if kwargs and "__serve_mux_id" in kwargs:
            mux_token = _set_request_model_id(kwargs.pop("__serve_mux_id"))
        self._ongoing += 1
        self._total += 1
        try:
            if self._is_function:
                fn = self._callable
            else:
                fn = getattr(self._callable, method_name or "__call__")
            if inspect.iscoroutinefunction(fn):
                out = fn(*args, **(kwargs or {}))
            else:
                # sync callables (jitted decode steps, blocking compute)
                # must not stall the actor loop — health checks and
                # concurrent requests ride the same loop. copy_context so
                # get_multiplexed_model_id() works off-loop too.
                import contextvars as _cv

                ctx = _cv.copy_context()
                out = await asyncio.get_running_loop().run_in_executor(
                    None, lambda: ctx.run(fn, *args, **(kwargs or {})))
            if inspect.isawaitable(out):
                out = await out
            if inspect.isgenerator(out) or inspect.isasyncgen(out):
                # streaming response: drain the generator into a queue the
                # caller pulls with stream_next (the chunk-pull transport
                # standing in for the reference's gRPC/ASGI streaming,
                # proxy.py:424)
                sid = self._next_stream_id
                self._next_stream_id += 1
                stream = _Stream(out)
                self._streams[sid] = stream
                asyncio.ensure_future(self._drain_stream(stream))
                if self._stream_reaper_task is None:
                    self._stream_reaper_task = asyncio.ensure_future(
                        self._stream_reaper())
                return {"__serve_stream__": sid}
            return out
        finally:
            self._ongoing -= 1
            if mux_token is not None:
                from ray_tpu.serve.multiplex import _request_model_id

                _request_model_id.reset(mux_token)

    async def handle_request_streaming(self, method_name: str, args, kwargs):
        """Streaming entry: an async generator the handle invokes with
        ``num_returns="streaming"`` — each yielded chunk becomes an
        owner-owned object the instant it is produced (native generator
        transport; ≈ the reference's handle.options(stream=True) riding
        ObjectRefGenerator instead of the chunk-pull `stream_next` path
        below, which remains for un-optioned callers)."""
        from ray_tpu.serve.multiplex import _set_request_model_id

        mux_token = None
        if kwargs and "__serve_mux_id" in kwargs:
            mux_token = _set_request_model_id(kwargs.pop("__serve_mux_id"))
        self._ongoing += 1
        self._total += 1
        try:
            if self._is_function:
                fn = self._callable
            else:
                fn = getattr(self._callable, method_name or "__call__")
            out = fn(*args, **(kwargs or {}))
            if inspect.isawaitable(out):
                out = await out
            if inspect.isasyncgen(out):
                async for item in out:
                    yield item
            elif inspect.isgenerator(out):
                # sync generator (e.g. a jitted decode step per token):
                # step it off-loop so health checks keep flowing, under
                # the request's contextvars (multiplexed model id)
                import contextvars as _cv

                from ray_tpu._private.async_utils import (END_OF_ITERATION,
                                                          step_off_loop)

                ctx = _cv.copy_context()
                while True:
                    item = await step_off_loop(lambda: next(out), ctx)
                    if item is END_OF_ITERATION:
                        break
                    yield item
            else:
                yield out  # non-streaming callable: single-chunk stream
        finally:
            self._ongoing -= 1
            if mux_token is not None:
                from ray_tpu.serve.multiplex import _request_model_id

                _request_model_id.reset(mux_token)

    async def _stream_reaper(self) -> None:
        """Abandoned streams (consumer gone mid-iteration) must not pump
        the generator, hold buffered chunks, or count as ongoing work for
        the replica's lifetime — reap proactively, not only on the next
        request."""
        while True:
            await asyncio.sleep(5.0)
            now = time.monotonic()
            for sid, stream in list(self._streams.items()):
                if now - stream.last_pull > self._stream_idle_ttl_s:
                    self._streams.pop(sid, None)
                    await stream.close()

    def _active_streams(self, window_s: float = 15.0) -> int:
        now = time.monotonic()
        return sum(1 for s in self._streams.values()
                   if now - s.last_pull < window_s)

    async def _drain_stream(self, stream: "_Stream") -> None:
        gen, q = stream.gen, stream.queue
        try:
            if inspect.isasyncgen(gen):
                async for item in gen:
                    await q.put(("item", item))
                    if stream.cancelled:
                        return
            else:
                # a sync generator's body (e.g. a jitted decode step per
                # token) must not block the actor loop: pump on a thread —
                # under the request's contextvars so the generator body
                # still sees get_multiplexed_model_id()
                import contextvars as _cv

                loop = asyncio.get_running_loop()
                ctx = _cv.copy_context()

                def pump():
                    def run():
                        for item in gen:
                            if stream.cancelled:
                                return
                            loop.call_soon_threadsafe(
                                q.put_nowait, ("item", item))

                    ctx.run(run)

                await loop.run_in_executor(None, pump)
            await q.put(("end", None))
        except Exception as e:  # noqa: BLE001 — crosses to the consumer
            await q.put(("error", f"{type(e).__name__}: {e}"))

    async def stream_next(self, stream_id: int, max_items: int = 256,
                          timeout_s: float = 10.0) -> Dict[str, Any]:
        """Pull the next buffered chunk(s) of a streaming response.
        Returns {items, done, error?}; an unknown id is a finished stream."""
        stream = self._streams.get(stream_id)
        if stream is None:
            return {"items": [], "done": True}
        q = stream.queue
        stream.last_pull = time.monotonic()
        items: list = []
        done = False
        error = None
        try:
            kind, item = await asyncio.wait_for(q.get(), timeout=timeout_s)
        except asyncio.TimeoutError:
            return {"items": [], "done": False}
        while True:
            if kind == "end":
                done = True
                break
            if kind == "error":
                done = True
                error = item
                break
            items.append(item)
            if len(items) >= max_items or q.empty():
                break
            kind, item = q.get_nowait()
        if done:
            self._streams.pop(stream_id, None)
        out: Dict[str, Any] = {"items": items, "done": done}
        if error is not None:
            out["error"] = error
        return out

    async def reconfigure(self, user_config: Any) -> None:
        if hasattr(self._callable, "reconfigure"):
            out = self._callable.reconfigure(user_config)
            if inspect.isawaitable(out):
                await out

    def _queue_depth(self) -> float:
        """Backlog the callable is holding beyond in-flight requests —
        the `ray_tpu_serve_queue_depth` signal. A callable exposes it via
        a `queue_depth()` method (the LLM server's continuous scheduler
        does); otherwise fall back to this process's gauge so any
        scheduler that sets the metric is covered."""
        probe = getattr(self._callable, "queue_depth", None)
        if callable(probe):
            try:
                return float(probe())
            except Exception:
                return 0.0
        try:
            from ray_tpu.serve._private.continuous import _m_queue_depth

            return float(_m_queue_depth.value())
        except Exception:
            return 0.0

    def _prefix_digest(self) -> Dict[str, Any]:
        """Prefix-affinity digest (ISSUE 18), relayed through stats so the
        controller's EXISTING poll carries it — replicas never originate a
        control-plane RPC for affinity."""
        probe = getattr(self._callable, "prefix_digest", None)
        if callable(probe):
            try:
                return probe() or {}
            except Exception:
                return {}
        return {}

    async def export_prefix(self, tokens, timeout_s: float = 30.0):
        """Migration pull entry (peer replica → this replica). The
        callable's scheduler does the radix match + gather on its own
        thread; run the blocking wait off the actor loop so health checks
        and requests keep flowing during a large export."""
        probe = getattr(self._callable, "export_prefix", None)
        if not callable(probe):
            return {"matched_len": 0, "page_tokens": 0, "k": [], "v": []}
        return await asyncio.get_running_loop().run_in_executor(
            None, lambda: probe(tokens, timeout_s=timeout_s))

    async def stats(self) -> Dict[str, Any]:
        # actively-consumed streams count as ongoing work for autoscaling;
        # abandoned ones must not pin the replica at scale. queue_depth
        # reports work ADMITTED but not yet scheduled (the continuous
        # batcher's pending queue) — in-flight counts alone undercount a
        # backlogged replica, which is exactly when scaling matters.
        return {"ongoing": self._ongoing + self._active_streams(),
                "queue_depth": self._queue_depth(),
                "total": self._total,
                "uptime_s": time.time() - self._started,
                "prefix_digest": self._prefix_digest()}

    async def check_health(self) -> bool:
        if hasattr(self._callable, "check_health"):
            out = self._callable.check_health()
            if inspect.isawaitable(out):
                out = await out
            return bool(out) if out is not None else True
        return True

    async def prepare_for_shutdown(self) -> None:
        # drain: wait for in-flight requests AND actively-consumed streams
        # (abandoned streams must not burn the drain window). The window
        # must exceed stream_next's 10s server-side pull wait — a consumer
        # blocked in a pull is active even though last_pull is aging.
        deadline = time.monotonic() + 10
        while ((self._ongoing > 0 or self._active_streams(window_s=15.0))
               and time.monotonic() < deadline):
            await asyncio.sleep(0.02)
