"""Continuous (iteration-level) batching scheduler for LLM serve replicas.

Replaces the flush-and-drain loop of ``@serve.batch`` for the LLM path
(ISSUE 9, ROADMAP item 4): instead of admitting a request batch, running
prefill plus the ENTIRE ``max_new_tokens`` decode loop, and only then
looking at the queue again, the scheduler owns a slotted KV-cache arena of
``slots`` sequence slots (``models.decode.SlotKVCache``) and drives ONE
fixed-shape jitted decode step over the whole arena per iteration:

  * new requests are admitted into free slots *between* decode iterations
    and prefilled in ``prefill_chunk``-token chunks (one chunk per
    iteration), so a long prompt can never stall in-flight decodes;
  * finished / EOS / cancelled sequences retire their slot immediately —
    the freed slot is re-admitted on the very next iteration;
  * every sampled token streams out to its request's asyncio queue the
    iteration it is produced, so streaming and non-streaming consumers ride
    the same batched program (no per-stream single-sequence decode loops).

This is the serving analog of PR 8's 1F1B pipeline loop: the device-side
program shape is compiled once and the host-side loop only decides *which*
sequences occupy which slots. All jax work runs on the scheduler's own
thread — the replica's asyncio event loop only ever touches queues.

Knobs: ``RAY_TPU_SERVE_SLOTS`` (arena width), ``RAY_TPU_SERVE_PREFILL_CHUNK``
(prefill chunk tokens); both overridable per-deployment via LLMServer init.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from functools import partial
from typing import Any, Dict, List, Optional

from ray_tpu._private import flight
from ray_tpu._private.metrics import Counter, Gauge

# flight-recorder span ids: the per-iteration admit/prefill/decode/retire
# phases the aggregate counters can't localize (per-thread ring records,
# no locks/RPCs — safe at decode-iteration rates)
_F_ADMIT = flight.intern("serve.admit")
_F_PREFILL = flight.intern("serve.prefill")
_F_DECODE = flight.intern("serve.decode")
_F_RETIRE = flight.intern("serve.retire")

_m_steps = Counter(
    "ray_tpu_serve_decode_steps_total",
    "Batched slot-arena decode iterations executed")
_m_prefill_chunks = Counter(
    "ray_tpu_serve_prefill_chunks_total",
    "Chunked prefill programs executed")
_m_tokens = Counter(
    "ray_tpu_serve_tokens_generated_total",
    "Tokens sampled and streamed out of the slot arena")
_m_admitted = Counter(
    "ray_tpu_serve_seqs_admitted_total",
    "Sequences admitted into a KV arena slot")
_m_retired = Counter(
    "ray_tpu_serve_seqs_retired_total",
    "Sequences retired from their slot (finished/EOS/cancelled/error)")
_m_active = Gauge(
    "ray_tpu_serve_slots_active",
    "KV arena slots currently holding a live sequence")
_m_queue_depth = Gauge(
    "ray_tpu_serve_queue_depth",
    "Requests waiting for a free KV arena slot")

# sequence states
_QUEUED = "queued"
_PREFILL = "prefill"
_DECODE = "decode"
_DONE = "done"


class SchedulerClosedError(RuntimeError):
    pass


class _Seq:
    """One in-flight generation request and its consumer-side queue."""

    __slots__ = ("prompt", "remaining_prompt", "max_new", "temperature",
                 "seed", "slot", "state", "n_generated", "next_token",
                 "queue", "loop", "cancelled", "t_submit", "t_first_token",
                 "rng")

    def __init__(self, prompt: List[int], max_new: int, temperature: float,
                 seed: int, loop, queue):
        self.prompt = prompt
        self.remaining_prompt = list(prompt)
        self.max_new = max_new
        self.temperature = temperature
        self.seed = seed
        self.slot: Optional[int] = None
        self.state = _QUEUED
        self.n_generated = 0
        self.next_token: Optional[int] = None
        self.queue = queue
        self.loop = loop
        self.cancelled = False
        self.t_submit = time.monotonic()
        self.t_first_token: Optional[float] = None
        self.rng = None  # lazily created numpy Generator for temperature > 0


class ContinuousScheduler:
    """Slotted-arena continuous-batching decode scheduler.

    ``params`` are the (device-resident) model parameters shared by every
    program; the scheduler owns the KV arena and two jitted programs —
    ``prefill_into_slot`` (one compiled shape: [1, prefill_chunk]) and
    ``slot_decode_step`` ([slots]) — both with donated caches so the arena
    updates in place instead of being copied per iteration.
    """

    def __init__(self, cfg, params, *, slots: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 arena_len: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 cache_dtype=None):
        import jax

        from ray_tpu._private.config import global_config
        from ray_tpu.models.decode import (init_slot_caches,
                                           prefill_into_slot,
                                           slot_decode_step)

        conf = global_config()
        self.cfg = cfg
        self.params = params
        # `is None` (not `or`): an explicit 0 must hit the validation
        # below, not silently take the config default (the PR-8 depth=0
        # lesson)
        self.slots = int(conf.serve_slots if slots is None else slots)
        self.prefill_chunk = int(conf.serve_prefill_chunk
                                 if prefill_chunk is None else prefill_chunk)
        self.arena_len = int(cfg.max_seq_len if arena_len is None
                             else arena_len)
        self.eos_id = eos_id
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {self.slots}")
        if self.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {self.prefill_chunk}")
        if self.prefill_chunk > self.arena_len:
            raise ValueError(
                f"prefill_chunk ({self.prefill_chunk}) exceeds the arena "
                f"length ({self.arena_len})")
        self._jax = jax
        # donated caches: the arena mutates in place across iterations
        self._prefill = jax.jit(partial(prefill_into_slot, cfg),
                                donate_argnums=(4,))
        self._step = jax.jit(partial(slot_decode_step, cfg),
                             donate_argnums=(3,))
        self._caches = init_slot_caches(cfg, self.slots, self.arena_len,
                                        cache_dtype)
        self._slot_seqs: List[Optional[_Seq]] = [None] * self.slots
        self._prefill_rr = 0  # round-robin cursor over prefilling slots
        self._pending: deque = deque()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._closed = False
        self._error: Optional[BaseException] = None
        # stats (host-side; mirrored into the process metric registry)
        self._n_steps = 0
        self._n_prefill_chunks = 0
        self._n_admitted = 0
        self._n_retired = 0
        self._n_tokens = 0
        self._admitted_mid_flight = 0
        self._max_active_slots = 0
        self._peak_queue_depth = 0
        self._thread = threading.Thread(
            target=self._run, name="serve-continuous-scheduler", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- submit

    def max_prompt_len(self, max_new: int) -> int:
        """Longest admissible prompt for a given generation budget: the
        padded prefill chunks AND prompt+new tokens must fit the arena."""
        c = self.prefill_chunk
        by_pad = (self.arena_len // c) * c
        return min(by_pad, self.arena_len - max_new)

    def submit(self, prompt_ids: List[int], *, max_new_tokens: int,
               temperature: float = 0.0, seed: int = 0,
               loop=None, queue=None) -> _Seq:
        """Enqueue a generation. Tokens/end/error events arrive on ``queue``
        via ``loop.call_soon_threadsafe`` as ``("tok", id)``, ``("end",
        reason)`` or ``("err", message)`` tuples. Thread/loop-safe."""
        if not prompt_ids:
            raise ValueError("prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt_ids) > self.max_prompt_len(max_new_tokens):
            raise ValueError(
                f"prompt of {len(prompt_ids)} tokens + {max_new_tokens} new "
                f"tokens does not fit a {self.arena_len}-token arena slot "
                f"(prefill pads prompts to {self.prefill_chunk}-token "
                f"chunks)")
        seq = _Seq(list(prompt_ids), max_new_tokens, temperature, seed,
                   loop, queue)
        with self._lock:
            if self._closed:
                raise SchedulerClosedError(
                    "scheduler is shut down" if self._error is None
                    else f"scheduler failed: {self._error!r}")
            self._pending.append(seq)
            self._peak_queue_depth = max(self._peak_queue_depth,
                                         len(self._pending))
            _m_queue_depth.set(float(len(self._pending)))
        self._wake.set()
        return seq

    def cancel(self, seq: _Seq) -> None:
        """Mark a sequence cancelled; its slot retires on the next
        iteration (pending sequences are dropped at admission)."""
        seq.cancelled = True
        self._wake.set()

    # -------------------------------------------------------------- loop

    def _emit(self, seq: _Seq, item) -> None:
        if seq.loop is None or seq.queue is None:
            return
        try:
            seq.loop.call_soon_threadsafe(seq.queue.put_nowait, item)
        except RuntimeError:
            # consumer's loop is gone — nobody is listening; retire quietly
            seq.cancelled = True

    def _retire(self, seq: _Seq, reason: str) -> None:
        if seq.slot is not None:
            flight.instant(_F_RETIRE, seq.slot)
            self._slot_seqs[seq.slot] = None
            seq.slot = None
        seq.state = _DONE
        self._n_retired += 1
        _m_retired.inc()
        self._emit(seq, ("end", reason))

    def _fail(self, seq: _Seq, msg: str) -> None:
        if seq.slot is not None:
            self._slot_seqs[seq.slot] = None
            seq.slot = None
        seq.state = _DONE
        self._n_retired += 1
        _m_retired.inc()
        self._emit(seq, ("err", msg))

    def _sample(self, seq: _Seq, logits_row) -> int:
        import numpy as np

        if seq.temperature <= 0.0:
            return int(np.asarray(logits_row).argmax())
        if seq.rng is None:
            seq.rng = np.random.default_rng(seq.seed)
        x = np.asarray(logits_row, np.float64) / seq.temperature
        x -= x.max()
        p = np.exp(x)
        p /= p.sum()
        return int(seq.rng.choice(len(p), p=p))

    def _emit_token(self, seq: _Seq, tok: int) -> bool:
        """Record + stream one sampled token; returns True if the sequence
        is finished (budget exhausted or EOS)."""
        seq.n_generated += 1
        self._n_tokens += 1
        _m_tokens.inc()
        if seq.t_first_token is None:
            seq.t_first_token = time.monotonic()
        self._emit(seq, ("tok", tok))
        if self.eos_id is not None and tok == self.eos_id:
            return True
        return seq.n_generated >= seq.max_new

    def _admit(self) -> None:
        from ray_tpu.models.decode import reset_slot

        while True:
            with self._lock:
                if not self._pending:
                    break
                free = next((i for i, s in enumerate(self._slot_seqs)
                             if s is None), None)
                if free is None:
                    break
                seq = self._pending.popleft()
                _m_queue_depth.set(float(len(self._pending)))
            if seq.cancelled:
                self._retire(seq, "cancelled")
                continue
            in_flight = any(s is not None for s in self._slot_seqs)
            seq.slot = free
            seq.state = _PREFILL
            self._slot_seqs[free] = seq
            self._caches = reset_slot(self._caches, free)
            self._n_admitted += 1
            flight.instant(_F_ADMIT, free)
            _m_admitted.inc()
            if in_flight:
                # the signal request-level flush-and-drain cannot produce:
                # an admission while other sequences are mid-generation
                self._admitted_mid_flight += 1

    def _prefill_one(self) -> bool:
        """Advance ONE prefilling sequence by one chunk, round-robin over
        slots — concurrent prompts interleave their chunks, so one long
        prompt cannot monopolize prefill (and decode never waits more than
        one chunk). Returns True if a chunk ran."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        start = self._prefill_rr
        for off in range(self.slots):
            i = (start + off) % self.slots
            seq = self._slot_seqs[i]
            if seq is None or seq.state != _PREFILL:
                continue
            self._prefill_rr = (i + 1) % self.slots
            if seq.cancelled:
                self._retire(seq, "cancelled")
                continue
            chunk = seq.remaining_prompt[:self.prefill_chunk]
            seq.remaining_prompt = seq.remaining_prompt[self.prefill_chunk:]
            real = len(chunk)
            padded = chunk + [0] * (self.prefill_chunk - real)
            tokens = jnp.asarray([padded], jnp.int32)
            t0 = flight.now()
            logits, self._caches = self._prefill(
                self.params, tokens, np.int32(real), np.int32(seq.slot),
                self._caches)
            if t0:
                # jax dispatch is async: without a sync the span would
                # time the DISPATCH and smear the real prefill compute
                # into the next decode region (the decode span gets its
                # sync from the np.asarray below)
                jax.block_until_ready(logits)
            flight.span_since(_F_PREFILL, t0)
            self._n_prefill_chunks += 1
            _m_prefill_chunks.inc()
            if not seq.remaining_prompt:
                # prompt fully resident: sample the first token NOW — this
                # is the time-to-first-token moment
                tok = self._sample(seq, logits)
                seq.state = _DECODE
                if self._emit_token(seq, tok):
                    self._retire(seq, "length" if self.eos_id is None
                                 or tok != self.eos_id else "eos")
                else:
                    seq.next_token = tok
            return True
        return False

    def _decode_once(self) -> bool:
        """One batched decode iteration over every DECODE slot."""
        import jax.numpy as jnp
        import numpy as np

        toks = np.zeros(self.slots, np.int32)
        active = np.zeros(self.slots, np.int32)
        live: List[_Seq] = []
        for i, seq in enumerate(self._slot_seqs):
            if seq is None or seq.state != _DECODE:
                continue
            if seq.cancelled:
                self._retire(seq, "cancelled")
                continue
            toks[i] = seq.next_token
            active[i] = 1
            live.append(seq)
        if not live:
            return False
        t0 = flight.now()
        logits, self._caches = self._step(
            self.params, jnp.asarray(toks), jnp.asarray(active),
            self._caches)
        la = np.asarray(logits)
        flight.span_since(_F_DECODE, t0)
        self._n_steps += 1
        _m_steps.inc()
        self._max_active_slots = max(self._max_active_slots, len(live))
        for seq in live:
            tok = self._sample(seq, la[seq.slot])
            if self._emit_token(seq, tok):
                self._retire(seq, "eos" if self.eos_id is not None
                             and tok == self.eos_id else "length")
            else:
                seq.next_token = tok
        return True

    def _run(self) -> None:
        try:
            while True:
                with self._lock:
                    if self._closed:
                        break
                self._admit()
                did = self._prefill_one()
                did = self._decode_once() or did
                _m_active.set(float(sum(
                    1 for s in self._slot_seqs if s is not None)))
                if not did:
                    with self._lock:
                        idle = not self._pending and all(
                            s is None or s.cancelled
                            for s in self._slot_seqs)
                        if idle:
                            self._wake.clear()
                    self._wake.wait(timeout=1.0)
        except BaseException as e:  # noqa: BLE001 — crosses to consumers
            self._error = e
            with self._lock:
                self._closed = True
            for seq in list(self._slot_seqs):
                if seq is not None:
                    self._fail(seq, f"{type(e).__name__}: {e}")
            with self._lock:
                pending = list(self._pending)
                self._pending.clear()
            for seq in pending:
                self._fail(seq, f"{type(e).__name__}: {e}")
        finally:
            with self._lock:
                self._closed = True
            _m_active.set(0.0)

    # --------------------------------------------------------- lifecycle

    def shutdown(self, timeout_s: float = 5.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pending = list(self._pending)
            self._pending.clear()
        self._wake.set()
        self._thread.join(timeout=timeout_s)
        for seq in pending:
            self._fail(seq, "scheduler shut down")
        for seq in list(self._slot_seqs):
            if seq is not None:
                self._fail(seq, "scheduler shut down")

    @property
    def closed(self) -> bool:
        return self._closed

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            q = len(self._pending)
        return {
            "mode": "continuous",
            "slots": self.slots,
            "prefill_chunk": self.prefill_chunk,
            "arena_len": self.arena_len,
            "decode_steps": self._n_steps,
            "prefill_chunks": self._n_prefill_chunks,
            "admitted": self._n_admitted,
            "retired": self._n_retired,
            "tokens_generated": self._n_tokens,
            # iteration-level proof signals: > 0 means a request was
            # admitted while others were mid-generation, which a
            # flush-and-drain batcher can never do
            "admitted_mid_flight": self._admitted_mid_flight,
            "max_active_slots": self._max_active_slots,
            "peak_queue_depth": self._peak_queue_depth,
            "queue_depth": q,
            "active_slots": sum(1 for s in self._slot_seqs if s is not None),
        }
