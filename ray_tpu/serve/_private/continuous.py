"""Continuous (iteration-level) batching scheduler for LLM serve replicas.

Replaces the flush-and-drain loop of ``@serve.batch`` for the LLM path
(ISSUE 9, ROADMAP item 4): instead of admitting a request batch, running
prefill plus the ENTIRE ``max_new_tokens`` decode loop, and only then
looking at the queue again, the scheduler owns a slotted KV-cache arena of
``slots`` sequence slots (``models.decode.SlotKVCache``) and drives ONE
fixed-shape jitted decode step over the whole arena per iteration:

  * new requests are admitted into free slots *between* decode iterations
    and prefilled in ``prefill_chunk``-token chunks (one chunk per
    iteration), so a long prompt can never stall in-flight decodes;
  * finished / EOS / cancelled sequences retire their slot immediately —
    the freed slot is re-admitted on the very next iteration;
  * every sampled token streams out to its request's asyncio queue the
    iteration it is produced, so streaming and non-streaming consumers ride
    the same batched program (no per-stream single-sequence decode loops).

This is the serving analog of PR 8's 1F1B pipeline loop: the device-side
program shape is compiled once and the host-side loop only decides *which*
sequences occupy which slots. All jax work runs on the scheduler's own
thread — the replica's asyncio event loop only ever touches queues.

ISSUE 13 rebuilds the arena as a PAGED pool (``kv_layout="paged"``, the
default): KV storage is a pool of ``page_tokens``-sized pages
(``models.decode.PagedKVCache``), each slot owns a page table instead of a
contiguous worst-case ``arena_len`` range, and the same two compiled
programs gather/scatter through the tables at fixed shapes — so long/idle
sequences stop reserving memory they never use and a replica admits far
more concurrent sequences at the same arena bytes. On top of paging a
PREFIX/RADIX CACHE (``serve/_private/paging.RadixCache``) makes admitting
a request whose prompt shares a cached prefix a page-table splice + cursor
jump instead of a re-prefill; eviction is LRU over refcount-0 nodes under
arena pressure. ``kv_layout="contiguous"`` keeps the PR-9 arena as the
measured baseline (the collective layer's ``algo="kv"`` idiom).

ISSUE 18 adds the FLEET phase on top: (1) the radix cache's chain-hash
digest is exported through ``prefix_digest()`` so the router can steer
prompts to the replica already holding their prefix; (2) a request that
arrives with a ``fleet_hint`` (holder replica handle + matched depth)
PULLS the matched prefix pages from the holder before admission — the
pull runs on a dedicated worker thread (the scheduler thread never
blocks on a peer), the pulled KV is spliced into the local arena +
radix tree, and admission then hits it like any local prefix; a failed
or timed-out pull falls back to a cold prefill, bit-identical by
construction; (3) speculative decoding: a ``speculative.Drafter``
proposes up to ``spec_k`` tokens per slot and ONE fixed-shape
``paged_verify_step`` call (the third and only third compiled program)
scores them all, with exact accept-prefix + corrected-resample
semantics (temperature-0 output is the sequential greedy path's, token
for token).

Knobs: ``RAY_TPU_SERVE_SLOTS`` (arena width), ``RAY_TPU_SERVE_PREFILL_CHUNK``
(prefill chunk tokens), ``RAY_TPU_SERVE_KV_LAYOUT``,
``RAY_TPU_SERVE_PAGE_TOKENS``, ``RAY_TPU_SERVE_KV_PAGES`` (0 = size the
pool to the contiguous worst case), ``RAY_TPU_SERVE_PREFIX_CACHE``,
``RAY_TPU_SERVE_MIGRATION_BUDGET`` (pages per cross-replica pull),
``RAY_TPU_SERVE_SPEC_K`` (draft tokens per verify round),
``RAY_TPU_SERVE_DRAFTER`` (drafter preset; ``"self"`` shares the target's
weights); all overridable per-deployment via LLMServer init.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from functools import partial
from queue import Empty as _QueueEmpty
from queue import Queue as _Queue
from typing import Any, Dict, List, Optional

from ray_tpu._private import flight
from ray_tpu._private.metrics import Counter, Gauge

# flight-recorder span ids: the per-iteration admit/prefill/decode/retire
# phases the aggregate counters can't localize (per-thread ring records,
# no locks/RPCs — safe at decode-iteration rates)
_F_ADMIT = flight.intern("serve.admit")
_F_PREFILL = flight.intern("serve.prefill")
_F_DECODE = flight.intern("serve.decode")
_F_RETIRE = flight.intern("serve.retire")
_F_VERIFY = flight.intern("serve.verify")
_F_MIGRATE = flight.intern("serve.migrate")
_F_ATTN = flight.intern("serve.attn")

_m_steps = Counter(
    "ray_tpu_serve_decode_steps_total",
    "Batched slot-arena decode iterations executed")
_m_prefill_chunks = Counter(
    "ray_tpu_serve_prefill_chunks_total",
    "Chunked prefill programs executed")
_m_tokens = Counter(
    "ray_tpu_serve_tokens_generated_total",
    "Tokens sampled and streamed out of the slot arena")
_m_admitted = Counter(
    "ray_tpu_serve_seqs_admitted_total",
    "Sequences admitted into a KV arena slot")
_m_retired = Counter(
    "ray_tpu_serve_seqs_retired_total",
    "Sequences retired from their slot (finished/EOS/cancelled/error)")
_m_active = Gauge(
    "ray_tpu_serve_slots_active",
    "KV arena slots currently holding a live sequence")
_m_attn_bytes = Counter(
    "ray_tpu_serve_attn_bytes_moved_total",
    "KV-cache bytes the paged attention lane streamed per program call "
    "(host-side mirror arithmetic, labelled by lane: the gather lane "
    "materializes the full provisioned arena, the in-place lanes only "
    "pages covering live tokens)")
_m_queue_depth = Gauge(
    "ray_tpu_serve_queue_depth",
    "Requests waiting for a free KV arena slot")

# sequence states
_QUEUED = "queued"
_PREFILL = "prefill"
_DECODE = "decode"
_DONE = "done"


class SchedulerClosedError(RuntimeError):
    pass


class _Seq:
    """One in-flight generation request and its consumer-side queue."""

    __slots__ = ("prompt", "remaining_prompt", "max_new", "temperature",
                 "seed", "slot", "state", "n_generated", "next_token",
                 "queue", "loop", "cancelled", "t_submit", "t_first_token",
                 "rng", "cached_len", "cursor", "owned_pages", "radix_node",
                 "table_fill", "fleet_hint", "migration_node",
                 "drafter_len", "drafter_pending")

    def __init__(self, prompt: List[int], max_new: int, temperature: float,
                 seed: int, loop, queue):
        self.prompt = prompt
        self.remaining_prompt = list(prompt)
        self.max_new = max_new
        self.temperature = temperature
        self.seed = seed
        self.slot: Optional[int] = None
        self.state = _QUEUED
        self.n_generated = 0
        self.next_token: Optional[int] = None
        self.queue = queue
        self.loop = loop
        self.cancelled = False
        self.t_submit = time.monotonic()
        self.t_first_token: Optional[float] = None
        self.rng = None  # lazily created numpy Generator for temperature > 0
        # ---- paged-arena bookkeeping (host mirrors of device state) ----
        self.cached_len = 0            # spliced prefix tokens (page-aligned)
        self.cursor = 0                # mirrors the slot's device cursor
        self.owned_pages: List[int] = []  # pages this slot must free
        self.radix_node = None         # ref-counted prefix-cache node
        self.table_fill = 0            # logical pages present in the table
        # ---- fleet phase (ISSUE 18) ----
        self.fleet_hint = None         # {"handle", "tokens"} from the router
        self.migration_node = None     # pin on a just-migrated prefix span
        # ---- speculative decoding (per-slot drafter sync state) ----
        self.drafter_len = -1          # drafter's valid context length
        self.drafter_pending: List[int] = []  # tokens drafter must catch up


class ContinuousScheduler:
    """Slotted-arena continuous-batching decode scheduler.

    ``params`` are the (device-resident) model parameters shared by every
    program; the scheduler owns the KV arena and two jitted programs —
    ``prefill_into_slot`` (one compiled shape: [1, prefill_chunk]) and
    ``slot_decode_step`` ([slots]) — both with donated caches so the arena
    updates in place instead of being copied per iteration.
    """

    def __init__(self, cfg, params, *, slots: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 arena_len: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 cache_dtype=None,
                 kv_layout: Optional[str] = None,
                 page_tokens: Optional[int] = None,
                 kv_pages: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 drafter=None,
                 spec_k: Optional[int] = None,
                 migration_budget: Optional[int] = None,
                 attn: Optional[str] = None):
        import numpy as np
        import jax

        from ray_tpu._private.config import global_config
        from ray_tpu.models.decode import (init_paged_caches,
                                           init_slot_caches,
                                           paged_decode_step,
                                           paged_prefill_into_slot,
                                           prefill_into_slot,
                                           slot_decode_step)

        conf = global_config()
        self.cfg = cfg
        self.params = params
        # `is None` (not `or`): an explicit 0 must hit the validation
        # below, not silently take the config default (the PR-8 depth=0
        # lesson)
        self.slots = int(conf.serve_slots if slots is None else slots)
        self.prefill_chunk = int(conf.serve_prefill_chunk
                                 if prefill_chunk is None else prefill_chunk)
        self.arena_len = int(cfg.max_seq_len if arena_len is None
                             else arena_len)
        self.eos_id = eos_id
        self.kv_layout = (conf.serve_kv_layout if kv_layout is None
                          else kv_layout)
        if self.kv_layout not in ("paged", "contiguous"):
            raise ValueError(
                f"kv_layout must be 'paged' or 'contiguous', got "
                f"{self.kv_layout!r}")
        self._paged = self.kv_layout == "paged"
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {self.slots}")
        if self.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {self.prefill_chunk}")
        if self.prefill_chunk > self.arena_len:
            raise ValueError(
                f"prefill_chunk ({self.prefill_chunk}) exceeds the arena "
                f"length ({self.arena_len})")
        self._jax = jax
        self._arena = None
        self._radix = None
        if self._paged:
            from ray_tpu.serve._private.paging import PageArena, RadixCache

            self.page_tokens = int(conf.serve_page_tokens
                                   if page_tokens is None else page_tokens)
            if self.page_tokens < 1:
                # explicit 0 (arg or RAY_TPU_SERVE_PAGE_TOKENS=0) raises —
                # never silently the config default through a falsy `or`
                raise ValueError(
                    f"page_tokens must be >= 1, got {self.page_tokens}")
            if self.arena_len % self.page_tokens != 0:
                raise ValueError(
                    f"arena_len ({self.arena_len}) must be a multiple of "
                    f"page_tokens ({self.page_tokens})")
            self._pages_per_slot = self.arena_len // self.page_tokens
            kvp = int(conf.serve_kv_pages if kv_pages is None else kv_pages)
            if kvp < 0:
                raise ValueError(f"kv_pages must be >= 0, got {kvp}")
            if kvp == 0:
                # auto: the contiguous worst case (every slot could fill
                # its whole logical range) + the reserved garbage page
                kvp = self.slots * self._pages_per_slot + 1
            self.num_pages = kvp
            self._arena = PageArena(self.num_pages, self.page_tokens)
            use_prefix = (conf.serve_prefix_cache if prefix_cache is None
                          else bool(prefix_cache))
            if use_prefix:
                self._radix = RadixCache(self._arena)
            # host-side page tables: logical page j of slot s lives at
            # physical page read_tables[s, j]; 0 = the garbage page
            # (unallocated reads are causally masked, redirected writes
            # are absorbed)
            self._read_tables = np.zeros(
                (self.slots, self._pages_per_slot), np.int32)
            self._write_tables = np.zeros(
                (self.slots, self._pages_per_slot), np.int32)
            from ray_tpu.ops.attention import resolve_paged_attn_lane

            # the attention lane resolves ONCE at build — a typo'd
            # RAY_TPU_SERVE_PAGED_ATTN fails the constructor, not some
            # later decode step, and stats() always names the real lane
            self.attn_lane = resolve_paged_attn_lane(
                conf.serve_paged_attn if attn is None else attn)
            # donated caches: the pool mutates in place across iterations;
            # the tables are tiny per-call host->device uploads
            self._prefill = jax.jit(
                partial(paged_prefill_into_slot, cfg, attn=self.attn_lane),
                donate_argnums=(6,))
            self._step = jax.jit(
                partial(paged_decode_step, cfg, attn=self.attn_lane),
                donate_argnums=(5,))
            self._caches = init_paged_caches(
                cfg, self.slots, self.num_pages, self.page_tokens,
                self._pages_per_slot, cache_dtype)
            self._kv_itemsize = int(self._caches[0].k.dtype.itemsize)
        else:
            from ray_tpu._private.config import env_flag_explicit

            if attn is not None:
                # the lane picks between paged attention programs; the
                # contiguous arena has no page tables to attend through,
                # so an explicit lane request here is a configuration bug
                raise ValueError(
                    "attn lane selection requires kv_layout='paged' "
                    "(the contiguous arena has no page tables)")
            self.attn_lane = None
            env_on = env_flag_explicit("serve_prefix_cache")
            if prefix_cache or (prefix_cache is None and env_on):
                # explicit intent conflicts loudly. "Explicit" means the
                # constructor arg or the env var (parsed by the config
                # layer's own bool rule); serve_prefix_cache=True arriving
                # through config is indistinguishable from the default
                # (which documents itself as paged-layout-only), so it
                # simply does not apply to the contiguous baseline
                raise ValueError(
                    "prefix_cache requires kv_layout='paged' (the "
                    "contiguous arena has no shareable pages)")
            self.page_tokens = 0
            self._pages_per_slot = 0
            self.num_pages = 0
            # donated caches: the arena mutates in place across iterations
            self._prefill = jax.jit(partial(prefill_into_slot, cfg),
                                    donate_argnums=(4,))
            self._step = jax.jit(partial(slot_decode_step, cfg),
                                 donate_argnums=(3,))
            self._caches = init_slot_caches(cfg, self.slots, self.arena_len,
                                            cache_dtype)
        # ---- speculative decoding (ISSUE 18): the drafter proposes, one
        # extra fixed-shape verify program scores — the two-compiles
        # contract becomes exactly three with speculation on
        self.spec_k = int(conf.serve_spec_k if spec_k is None else spec_k)
        if self.spec_k < 1:
            # explicit 0 (arg or RAY_TPU_SERVE_SPEC_K=0) raises — never
            # silently the config default through a falsy `or`
            raise ValueError(f"spec_k must be >= 1, got {self.spec_k}")
        self.migration_budget = int(conf.serve_migration_budget
                                    if migration_budget is None
                                    else migration_budget)
        if self.migration_budget < 1:
            raise ValueError(f"migration_budget must be >= 1, got "
                             f"{self.migration_budget}")
        self._drafter = drafter
        self._verify = None
        if drafter is not None:
            if not self._paged:
                raise ValueError(
                    "speculative decoding requires kv_layout='paged' (the "
                    "verify step scores K tokens through page tables)")
            if drafter.slots != self.slots:
                raise ValueError(
                    f"drafter has {drafter.slots} slots, scheduler has "
                    f"{self.slots} — they must share the slot numbering")
            from ray_tpu.models.decode import paged_verify_step

            self._verify = jax.jit(
                partial(paged_verify_step, cfg, attn=self.attn_lane),
                donate_argnums=(4,))
        # ---- cross-replica page migration (ISSUE 18): a dedicated
        # worker thread does the blocking peer pull; the scheduler thread
        # only splices finished results between iterations. _commands
        # carries EXPORT requests from peer replicas (RPC threads) onto
        # the scheduler thread, which owns the radix tree and the caches.
        self._migrating: List[_Seq] = []
        self._mig_requests: _Queue = _Queue()
        self._mig_results: _Queue = _Queue()
        self._mig_thread: Optional[threading.Thread] = None
        self._commands: deque = deque()
        self._n_migrations = 0
        self._n_migrated_pages = 0
        self._n_migration_failures = 0
        self._n_spec_rounds = 0
        self._n_drafted = 0
        self._n_accepted = 0
        self._n_spec_emitted = 0
        self._slot_seqs: List[Optional[_Seq]] = [None] * self.slots
        self._prefill_rr = 0  # round-robin cursor over prefilling slots
        self._pending: deque = deque()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._closed = False
        self._error: Optional[BaseException] = None
        # stats (host-side; mirrored into the process metric registry)
        self._n_steps = 0
        self._n_prefill_chunks = 0
        self._n_admitted = 0
        self._n_retired = 0
        self._n_tokens = 0
        self._n_attn_bytes = 0
        self._n_prefix_hit_tokens = 0
        self._admitted_mid_flight = 0
        self._max_active_slots = 0
        self._peak_queue_depth = 0
        self._thread = threading.Thread(
            target=self._run, name="serve-continuous-scheduler", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- submit

    def max_prompt_len(self, max_new: int) -> int:
        """Longest admissible prompt for a given generation budget: the
        padded prefill chunks AND prompt+new tokens must fit the arena.
        Page-aware: with a paged pool smaller than one slot's worst case,
        the whole-pool page budget also caps a single sequence — an
        over-budget request is rejected loudly at submit, before any
        pages are allocated."""
        c = self.prefill_chunk
        effective = self.arena_len
        if self._paged:
            effective = min(effective,
                            self._arena.usable_pages * self.page_tokens)
        # with speculation on, a verify round near the end of generation
        # writes up to spec_k positions past the final cursor — reserve
        # them so the windowed scatter can never clip onto the slot's
        # last real page
        reserve = self.spec_k if self._drafter is not None else 0
        by_pad = (effective // c) * c
        return min(by_pad, effective - max_new - reserve)

    def submit(self, prompt_ids: List[int], *, max_new_tokens: int,
               temperature: float = 0.0, seed: int = 0,
               loop=None, queue=None, fleet_hint=None) -> _Seq:
        """Enqueue a generation. Tokens/end/error events arrive on ``queue``
        via ``loop.call_soon_threadsafe`` as ``("tok", id)``, ``("end",
        reason)`` or ``("err", message)`` tuples. Thread/loop-safe.

        ``fleet_hint`` (router-attached): ``{"handle": holder_replica,
        "tokens": matched_depth}`` — before admission the scheduler pulls
        the matched prefix pages from the holder and splices them locally;
        any pull failure degrades to a cold prefill."""
        if not prompt_ids:
            raise ValueError("prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt_ids) > self.max_prompt_len(max_new_tokens):
            raise ValueError(
                f"prompt of {len(prompt_ids)} tokens + {max_new_tokens} new "
                f"tokens does not fit a {self.arena_len}-token arena slot "
                f"(prefill pads prompts to {self.prefill_chunk}-token "
                f"chunks)")
        seq = _Seq(list(prompt_ids), max_new_tokens, temperature, seed,
                   loop, queue)
        if fleet_hint and self._paged and self._radix is not None:
            seq.fleet_hint = dict(fleet_hint)
        with self._lock:
            if self._closed:
                raise SchedulerClosedError(
                    "scheduler is shut down" if self._error is None
                    else f"scheduler failed: {self._error!r}")
            self._pending.append(seq)
            self._peak_queue_depth = max(self._peak_queue_depth,
                                         len(self._pending))
            _m_queue_depth.set(float(len(self._pending)))
        self._wake.set()
        return seq

    def cancel(self, seq: _Seq) -> None:
        """Mark a sequence cancelled; its slot retires on the next
        iteration (pending sequences are dropped at admission)."""
        seq.cancelled = True
        self._wake.set()

    # -------------------------------------------------------------- loop

    def _emit(self, seq: _Seq, item) -> None:
        if seq.loop is None or seq.queue is None:
            return
        try:
            seq.loop.call_soon_threadsafe(seq.queue.put_nowait, item)
        except RuntimeError:
            # consumer's loop is gone — nobody is listening; retire quietly
            seq.cancelled = True

    def _release_slot_resources(self, seq: _Seq) -> None:
        """Paged-arena teardown for one slot: drop the prefix-cache ref,
        free owned pages, and zero the page-table rows (so an inactive
        slot's decode gather/scatter touches only the garbage page)."""
        if not self._paged or seq.slot is None:
            return
        slot = seq.slot
        if seq.radix_node is not None:
            self._radix.release(seq.radix_node)
            seq.radix_node = None
        if seq.owned_pages:
            self._arena.free(seq.owned_pages)
            seq.owned_pages = []
        seq.table_fill = 0
        self._read_tables[slot, :] = 0
        self._write_tables[slot, :] = 0

    def _release_migration_ref(self, seq: _Seq) -> None:
        """A migrated-prefix pin must drop no matter how the sequence
        ends — including cancellation BEFORE it ever took a slot (the
        node ref is held while the sequence waits in the pending queue)."""
        if seq.migration_node is not None and self._radix is not None:
            self._radix.release(seq.migration_node)
            seq.migration_node = None

    def _retire(self, seq: _Seq, reason: str) -> None:
        self._release_migration_ref(seq)
        self._release_slot_resources(seq)
        if seq.slot is not None:
            flight.instant(_F_RETIRE, seq.slot)
            self._slot_seqs[seq.slot] = None
            seq.slot = None
        seq.state = _DONE
        self._n_retired += 1
        _m_retired.inc()
        self._emit(seq, ("end", reason))

    def _fail(self, seq: _Seq, msg: str) -> None:
        self._release_migration_ref(seq)
        self._release_slot_resources(seq)
        if seq.slot is not None:
            self._slot_seqs[seq.slot] = None
            seq.slot = None
        seq.state = _DONE
        self._n_retired += 1
        _m_retired.inc()
        self._emit(seq, ("err", msg))

    def _ensure_pages(self, seq: _Seq, upto: int) -> bool:
        """Grow the slot's page table so its logical view covers
        [0, upto) tokens, evicting LRU unreferenced prefix-cache nodes
        under pressure. On exhaustion the SEQUENCE fails cleanly (the
        scheduler and its other slots keep running). Returns True if the
        pages are present."""
        from ray_tpu.serve._private.paging import OutOfPagesError

        need = -(-upto // self.page_tokens)
        missing = need - seq.table_fill
        if missing <= 0:
            return True
        try:
            pages = self._arena.alloc(missing)
        except OutOfPagesError:
            if self._radix is not None:
                self._radix.evict(missing - self._arena.free_pages)
            try:
                pages = self._arena.alloc(missing)
            except OutOfPagesError:
                self._fail(seq, f"kv arena out of pages (need {missing} "
                                f"more, {self._arena.free_pages} free of "
                                f"{self._arena.usable_pages}; nothing "
                                f"evictable)")
                return False
        slot = seq.slot
        for j, p in enumerate(pages, start=seq.table_fill):
            self._read_tables[slot, j] = p
            self._write_tables[slot, j] = p
        seq.owned_pages.extend(pages)
        seq.table_fill = need
        return True

    def _sample(self, seq: _Seq, logits_row) -> int:
        import numpy as np

        if seq.temperature <= 0.0:
            return int(np.asarray(logits_row).argmax())
        if seq.rng is None:
            seq.rng = np.random.default_rng(seq.seed)
        x = np.asarray(logits_row, np.float64) / seq.temperature
        x -= x.max()
        p = np.exp(x)
        p /= p.sum()
        return int(seq.rng.choice(len(p), p=p))

    def _emit_token(self, seq: _Seq, tok: int) -> bool:
        """Record + stream one sampled token; returns True if the sequence
        is finished (budget exhausted or EOS)."""
        seq.n_generated += 1
        self._n_tokens += 1
        _m_tokens.inc()
        if seq.t_first_token is None:
            seq.t_first_token = time.monotonic()
        self._emit(seq, ("tok", tok))
        if self.eos_id is not None and tok == self.eos_id:
            return True
        return seq.n_generated >= seq.max_new

    def _splice_prefix(self, seq: _Seq) -> None:
        """Prefix-cache lookup at admission: splice the longest cached
        page-aligned prefix of the prompt into the slot's read table
        (write entries stay on the garbage page — shared pages are
        immutable) and jump the cursor past it. The last prompt token is
        never matched: it must re-prefill to produce the first sampled
        token's logits. The splice is clamped so the remaining tail's
        padded chunks still fit the logical view (chunks restart at the
        cursor, which is page- but not chunk-aligned)."""
        pages, matched, node = self._radix.match(seq.prompt[:-1])
        if matched == 0:
            self._radix.note_miss()
            return
        T, C = self.page_tokens, self.prefill_chunk
        keep = matched
        while keep > 0:
            rem = len(seq.prompt) - keep
            if keep + (-(-rem // C)) * C <= self.arena_len:
                break
            keep -= T
        if keep <= 0:
            # the whole match was clamped away — nothing avoided, so this
            # is a MISS for the hit-rate metrics
            self._radix.release(node)
            self._radix.note_miss()
            return
        self._radix.note_hit(keep)
        n = keep // T
        self._read_tables[seq.slot, :n] = pages[:n]
        seq.cached_len = keep
        seq.table_fill = n
        seq.radix_node = node
        self._n_prefix_hit_tokens += keep

    def _admit(self) -> None:
        from ray_tpu.models.decode import paged_reset_slot, reset_slot

        while True:
            with self._lock:
                if not self._pending:
                    break
                free = next((i for i, s in enumerate(self._slot_seqs)
                             if s is None), None)
                if free is None:
                    break
                seq = self._pending.popleft()
                _m_queue_depth.set(float(len(self._pending)))
            if seq.cancelled:
                self._retire(seq, "cancelled")
                continue
            in_flight = any(s is not None for s in self._slot_seqs)
            seq.slot = free
            seq.state = _PREFILL
            self._slot_seqs[free] = seq
            if self._paged:
                seq.cached_len = 0
                seq.owned_pages = []
                seq.radix_node = None
                seq.table_fill = 0
                self._read_tables[free, :] = 0
                self._write_tables[free, :] = 0
                if self._radix is not None:
                    self._splice_prefix(seq)
                    # a migrated prefix was pinned only so eviction could
                    # not race admission; the splice holds its own ref now
                    self._release_migration_ref(seq)
                seq.cursor = seq.cached_len
                seq.remaining_prompt = seq.prompt[seq.cached_len:]
                self._caches = paged_reset_slot(self._caches, free,
                                                seq.cached_len)
            else:
                self._caches = reset_slot(self._caches, free)
            self._n_admitted += 1
            flight.instant(_F_ADMIT, free)
            _m_admitted.inc()
            if in_flight:
                # the signal request-level flush-and-drain cannot produce:
                # an admission while other sequences are mid-generation
                self._admitted_mid_flight += 1

    def _record_attn(self, t0: int, qk: int, n_slots: int,
                     longest: Optional[int] = None) -> None:
        """Stamp the ``serve.attn`` span and account the KV bytes the
        attention lane streamed for one attention-bearing program call.
        Pure host-side mirror arithmetic (cursors, table shapes) — no
        device readback on the hot loop. The gather lane materializes a
        contiguous ``[pages_per_slot * page_tokens]`` view per slot per
        layer regardless of how little of it is live; the in-place lanes
        stream only pages covering the longest live sequence."""
        if not self._paged:
            return
        flight.span_since(_F_ATTN, t0)
        cfg = self.cfg
        T = self.page_tokens
        row = cfg.kv_heads * cfg.head_dim * self._kv_itemsize
        if self.attn_lane == "gather":
            pages = n_slots * self._pages_per_slot
        else:
            if longest is None:
                longest = max((s.cursor for s in self._slot_seqs
                               if s is not None), default=0)
            pages = n_slots * min(-(-(longest + qk) // T),
                                  self._pages_per_slot)
        # k + v pools, every layer: pages read through the table plus the
        # qk freshly-written rows per slot
        moved = 2 * cfg.num_layers * row * (pages * T + n_slots * qk)
        self._n_attn_bytes += moved
        _m_attn_bytes.inc(moved, labels={"lane": self.attn_lane})

    def _prefill_one(self) -> bool:
        """Advance ONE prefilling sequence by one chunk, round-robin over
        slots — concurrent prompts interleave their chunks, so one long
        prompt cannot monopolize prefill (and decode never waits more than
        one chunk). Returns True if a chunk ran."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        start = self._prefill_rr
        for off in range(self.slots):
            i = (start + off) % self.slots
            seq = self._slot_seqs[i]
            if seq is None or seq.state != _PREFILL:
                continue
            self._prefill_rr = (i + 1) % self.slots
            if seq.cancelled:
                self._retire(seq, "cancelled")
                continue
            # pages are needed only up to the REAL tokens this chunk
            # writes — pad positions beyond them land on unallocated
            # table entries, which the garbage-page write redirect
            # absorbs by design (don't fail a fitting sequence for
            # pad-only pages when the pool is tight)
            if self._paged and not self._ensure_pages(
                    seq, seq.cursor + min(len(seq.remaining_prompt),
                                          self.prefill_chunk)):
                continue  # failed cleanly; other slots keep running
            chunk = seq.remaining_prompt[:self.prefill_chunk]
            seq.remaining_prompt = seq.remaining_prompt[self.prefill_chunk:]
            real = len(chunk)
            padded = chunk + [0] * (self.prefill_chunk - real)
            tokens = jnp.asarray([padded], jnp.int32)
            t0 = flight.now()
            if self._paged:
                logits, self._caches = self._prefill(
                    self.params, tokens, np.int32(real), np.int32(seq.slot),
                    jnp.asarray(self._read_tables[seq.slot]),
                    jnp.asarray(self._write_tables[seq.slot]),
                    self._caches)
                seq.cursor += real
            else:
                logits, self._caches = self._prefill(
                    self.params, tokens, np.int32(real), np.int32(seq.slot),
                    self._caches)
            if t0:
                # jax dispatch is async: without a sync the span would
                # time the DISPATCH and smear the real prefill compute
                # into the next decode region (the decode span gets its
                # sync from the np.asarray below)
                jax.block_until_ready(logits)
            flight.span_since(_F_PREFILL, t0)
            if self._paged:
                self._record_attn(t0, self.prefill_chunk, 1,
                                  longest=seq.cursor - real)
            self._n_prefill_chunks += 1
            _m_prefill_chunks.inc()
            if self._paged and self._radix is not None \
                    and not seq.remaining_prompt:
                self._offer_prompt_pages(seq)
            if not seq.remaining_prompt:
                # prompt fully resident: sample the first token NOW — this
                # is the time-to-first-token moment
                tok = self._sample(seq, logits)
                seq.state = _DECODE
                if self._emit_token(seq, tok):
                    self._retire(seq, "length" if self.eos_id is None
                                 or tok != self.eos_id else "eos")
                else:
                    seq.next_token = tok
            return True
        return False

    def _offer_prompt_pages(self, seq: _Seq) -> None:
        """Prompt fully resident: offer its full pages to the radix cache
        so a later admit with the same prefix splices instead of
        re-prefilling. Pages the tree adopts become shared read-only
        (write-table entries redirect to the garbage page — they are
        never written again anyway: pads and decode tokens land at
        positions >= the prompt length, i.e. in later pages); spans
        another sequence cached first stay slot-owned duplicates. The
        slot swaps its admission-time node ref for the deeper inserted
        node, which pins the whole path against eviction while it
        decodes."""
        T = self.page_tokens
        ins_len = (len(seq.prompt) // T) * T
        if ins_len <= seq.cached_len:
            return
        n = ins_len // T
        slot = seq.slot
        offered = [int(x) for x in self._read_tables[slot, :n]]
        dups, node = self._radix.insert(seq.prompt[:ins_len], offered)
        adopted = set(offered) - set(dups)
        if adopted:
            seq.owned_pages = [p for p in seq.owned_pages
                               if p not in adopted]
            for j in range(n):
                if int(self._write_tables[slot, j]) in adopted:
                    self._write_tables[slot, j] = 0
        if node is not None:
            if seq.radix_node is not None:
                self._radix.release(seq.radix_node)
            seq.radix_node = node

    # ------------------------------------------- cross-replica migration

    def _requeue(self, seq: _Seq) -> None:
        with self._lock:
            self._pending.appendleft(seq)
            _m_queue_depth.set(float(len(self._pending)))

    def _ensure_mig_thread(self) -> None:
        if self._mig_thread is None:
            t = threading.Thread(target=self._migration_worker,
                                 name="serve-migration-puller", daemon=True)
            self._mig_thread = t
            t.start()

    def _migration_worker(self) -> None:
        """Blocking peer pulls live here, NEVER on the scheduler thread —
        a dead or slow holder must not stall in-flight decodes. The pull
        is replica→replica (PR-2 pull idiom): the controller is not on
        the data path."""
        import ray_tpu

        while True:
            item = self._mig_requests.get()
            if item is None:
                return
            seq, handle, tokens = item
            try:
                res = ray_tpu.get(handle.export_prefix.remote(list(tokens)),
                                  timeout=30.0)
            except Exception as e:  # noqa: BLE001 — any failure = cold path
                res = {"__error__": f"{type(e).__name__}: {e}"}
            self._mig_results.put((seq, res))
            self._wake.set()

    def _start_migrations(self) -> None:
        """Pre-admission pass: pending sequences carrying a router fleet
        hint are parked in ``_migrating`` while the worker pulls their
        prefix from the holder. The want-length is page-aligned, clamped
        to what is NOT already cached locally, and bounded by the
        migration budget — a hint that buys nothing re-queues for normal
        (cold or locally-warm) admission immediately."""
        if not self._paged or self._radix is None:
            return
        with self._lock:
            flagged = [s for s in self._pending if s.fleet_hint is not None]
            for s in flagged:
                self._pending.remove(s)
            if flagged:
                _m_queue_depth.set(float(len(self._pending)))
        for seq in flagged:
            hint = seq.fleet_hint or {}
            seq.fleet_hint = None
            handle = hint.get("handle")
            hint_tokens = int(hint.get("tokens") or 0)
            if seq.cancelled:
                self._retire(seq, "cancelled")
                continue
            T = self.page_tokens
            _pages, matched, node = self._radix.match(seq.prompt[:-1])
            if node is not None:
                self._radix.release(node)
            want = min(hint_tokens, len(seq.prompt) - 1)
            want = (want // T) * T
            want = min(want, matched + self.migration_budget * T)
            if handle is None or want <= matched:
                self._requeue(seq)
                continue
            self._ensure_mig_thread()
            self._migrating.append(seq)
            self._mig_requests.put((seq, handle, seq.prompt[:want]))

    def _finish_migrations(self) -> None:
        """Drain completed pulls (success or failure) and re-queue their
        sequences for normal admission — a successful splice means the
        admission-time ``_splice_prefix`` now hits the migrated span, a
        failed pull means a plain cold prefill. Either way the OUTPUT is
        the same tokens; migration only moves where the KV comes from."""
        if not self._paged:
            return
        while True:
            try:
                seq, res = self._mig_results.get_nowait()
            except _QueueEmpty:
                return
            try:
                self._migrating.remove(seq)
            except ValueError:
                pass
            if seq.state == _DONE:
                continue
            if seq.cancelled:
                self._retire(seq, "cancelled")
                continue
            ok = (isinstance(res, dict) and "__error__" not in res
                  and int(res.get("matched_len") or 0) > 0
                  and int(res.get("page_tokens") or 0) == self.page_tokens)
            if ok:
                try:
                    self._splice_migrated(seq, res)
                except Exception:  # noqa: BLE001 — abandon to cold prefill
                    self._n_migration_failures += 1
            else:
                self._n_migration_failures += 1
            self._requeue(seq)

    def _splice_migrated(self, seq: _Seq, res: Dict[str, Any]) -> None:
        """Copy pulled prefix KV into freshly-allocated local pages and
        insert the span into the radix tree (pinned via the sequence's
        ``migration_node`` until admission splices it). Any failure —
        allocation, shape, dtype — propagates to the caller, which counts
        it and lets the sequence prefill cold; nothing here is ever
        half-applied: pages are only reachable once ``insert`` succeeds."""
        import dataclasses

        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.serve._private.affinity import (m_migrated_pages,
                                                     m_migrations)
        from ray_tpu.serve._private.paging import OutOfPagesError

        T = self.page_tokens
        matched = (int(res["matched_len"]) // T) * T
        n = matched // T
        if n <= 0:
            raise ValueError("empty migration payload")
        t0 = flight.now()
        try:
            pages = self._arena.alloc(n)
        except OutOfPagesError:
            self._radix.evict(n - self._arena.free_pages)
            pages = self._arena.alloc(n)
        try:
            idx = jnp.asarray(np.asarray(pages, np.int32))
            out = []
            for li, c in enumerate(self._caches):
                k = jnp.asarray(np.asarray(res["k"][li]), c.k.dtype)
                v = jnp.asarray(np.asarray(res["v"][li]), c.v.dtype)
                out.append(dataclasses.replace(
                    c, k=c.k.at[idx].set(k), v=c.v.at[idx].set(v)))
            self._jax.block_until_ready(out[0].k)
            self._caches = out
            dups, node = self._radix.insert(seq.prompt[:matched], pages)
        except BaseException:
            self._arena.free(pages)
            raise
        if dups:
            # spans another sequence cached while we pulled: keep theirs
            self._arena.free(dups)
        if node is not None:
            seq.migration_node = node
        self._n_migrations += 1
        self._n_migrated_pages += n - len(dups)
        m_migrations.inc()
        m_migrated_pages.inc(n - len(dups))
        flight.span_since(_F_MIGRATE, t0)

    # -------------------------------------------------- prefix export

    def export_prefix(self, tokens: List[int],
                      timeout_s: float = 30.0) -> Dict[str, Any]:
        """Serve a migration pull FROM a peer replica. Called on an RPC
        thread; the actual radix match + device gather must run on the
        scheduler thread (sole owner of the tree and the donated caches),
        so this enqueues a command and waits. The matched node is pinned
        only for the duration of the gather."""
        if not self._paged or self._radix is None:
            return {"matched_len": 0, "page_tokens": self.page_tokens,
                    "k": [], "v": []}
        box: Dict[str, Any] = {}
        done = threading.Event()
        with self._lock:
            if self._closed:
                raise SchedulerClosedError("scheduler is shut down")
            self._commands.append((list(tokens), box, done))
        self._wake.set()
        if not done.wait(timeout=timeout_s):
            raise TimeoutError(
                f"export_prefix timed out after {timeout_s:.0f}s")
        if "error" in box:
            raise RuntimeError(box["error"])
        return box["result"]

    def _process_commands(self) -> None:
        while self._commands:
            try:
                tokens, box, done = self._commands.popleft()
            except IndexError:
                return
            try:
                box["result"] = self._export_prefix_now(tokens)
            except BaseException as e:  # noqa: BLE001 — crosses threads
                box["error"] = f"{type(e).__name__}: {e}"
            done.set()

    def _export_prefix_now(self, tokens: List[int]) -> Dict[str, Any]:
        import numpy as np

        pages, matched, node = self._radix.match(tokens)
        if matched == 0:
            return {"matched_len": 0, "page_tokens": self.page_tokens,
                    "k": [], "v": []}
        n = matched // self.page_tokens
        idx = np.asarray(pages[:n], np.int32)
        ks, vs = [], []
        try:
            for c in self._caches:
                ks.append(np.asarray(c.k[idx]))
                vs.append(np.asarray(c.v[idx]))
        finally:
            self._radix.release(node)
        return {"matched_len": n * self.page_tokens,
                "page_tokens": self.page_tokens, "k": ks, "v": vs}

    def prefix_digest(self) -> Dict[str, Any]:
        """Chain-hash digest of the radix cache for the affinity router.
        Probed OFF the scheduler thread (the stats path), so the rare
        mid-mutation dict iteration is retried rather than locked — the
        digest is advisory; a stale read costs one cold prefill at most."""
        if not self._paged or self._radix is None:
            return {}
        for _ in range(8):
            try:
                return self._radix.digest()
            except RuntimeError:
                continue
        return {}

    # ------------------------------------------------ speculative decode

    def _prime_drafter(self, seq: _Seq) -> None:
        """First speculative round for a freshly-decoding slot: give the
        drafter the sequence's full context up to the cursor. A drafter
        sharing the target's params ADOPTS the paged KV by gather (prefix
        splices included — the TTFT win survives); a distinct drafter
        must run the prompt through its own model."""
        if self._drafter.shares_target:
            self._drafter.adopt_from_paged(
                seq.slot, self._caches, self._read_tables[seq.slot],
                int(seq.cursor), self.page_tokens)
        else:
            self._drafter.prefill_prompt(seq.slot, seq.prompt,
                                         self.prefill_chunk)
        seq.drafter_len = int(seq.cursor)
        seq.drafter_pending = []

    def _decode_spec(self) -> bool:
        """One speculative round over every DECODE slot: exactly
        ``spec_k`` batched drafter steps propose tokens, ONE fixed-shape
        ``paged_verify_step`` scores every proposal, and exact
        accept-prefix + corrected-resample emits 1..spec_k+1 tokens per
        live sequence. Rejections rewind CURSORS only (host-side) — pages
        are never freed or mutated by a rejection; stale KV past a cursor
        is causally masked until overwritten.

        Drafter sync: the drafter always steps ``spec_k`` times (fixed
        program shapes), but after a fully-accepted round it first
        catches up on the accepted token it never consumed
        (``drafter_pending``), producing one fewer draft that round."""
        import numpy as np

        import jax.numpy as jnp

        from ray_tpu.models.decode import paged_rewind_slots
        from ray_tpu.serve._private.speculative import (_softmax,
                                                        accept_greedy,
                                                        accept_sample,
                                                        m_spec_accepted,
                                                        m_spec_drafted)

        k = self.spec_k
        K = k + 1
        live: List[_Seq] = []
        for seq in self._slot_seqs:
            if seq is None or seq.state != _DECODE:
                continue
            if seq.cancelled:
                self._retire(seq, "cancelled")
                continue
            # the verify scatter writes positions [cursor, cursor + K)
            if not self._ensure_pages(seq, seq.cursor + K):
                continue
            live.append(seq)
        if not live:
            return False
        for seq in live:
            if seq.drafter_len < 0:
                self._prime_drafter(seq)
        # ---- draft: k batched drafter steps, sampled host-side --------
        feed = {s.slot: list(s.drafter_pending) + [s.next_token]
                for s in live}
        pend0 = {s.slot: list(s.drafter_pending) for s in live}
        drafts: Dict[int, List[int]] = {s.slot: [] for s in live}
        dprobs: Dict[int, List[Any]] = {s.slot: [] for s in live}
        toks = np.zeros(self.slots, np.int32)
        active = np.zeros(self.slots, np.int32)
        for s in live:
            active[s.slot] = 1
        for _ in range(k):
            for s in live:
                sl = s.slot
                toks[sl] = feed[sl].pop(0) if feed[sl] else drafts[sl][-1]
            la = self._drafter.step(toks, active)
            for s in live:
                sl = s.slot
                if feed[sl]:
                    continue  # still catching up; not at the draft frontier
                if s.temperature <= 0.0:
                    d = int(la[sl].argmax())
                else:
                    if s.rng is None:
                        s.rng = np.random.default_rng(s.seed)
                    p = _softmax(la[sl], s.temperature)
                    dprobs[sl].append(p)
                    d = int(s.rng.choice(len(p), p=p))
                drafts[sl].append(d)
        # ---- verify: ONE fixed-shape K-token target call --------------
        vt = np.zeros((self.slots, K), np.int32)
        for s in live:
            row = [s.next_token] + drafts[s.slot]
            vt[s.slot, :len(row)] = row
        t0 = flight.now()
        vlogits, self._caches = self._verify(
            self.params, jnp.asarray(vt),
            jnp.asarray(self._read_tables),
            jnp.asarray(self._write_tables), self._caches)
        va = np.asarray(vlogits)
        flight.span_since(_F_VERIFY, t0)
        self._record_attn(t0, K, self.slots)
        self._n_steps += 1
        _m_steps.inc()
        self._n_spec_rounds += 1
        self._max_active_slots = max(self._max_active_slots, len(live))
        # ---- exact acceptance + host-side cursor rewind ---------------
        new_lengths = np.asarray(self._caches[0].lengths, np.int32).copy()
        dlen = self._drafter.lengths().copy()
        for s in live:
            sl = s.slot
            ds = drafts[sl]
            old = s.cursor
            nxt = s.next_token
            if s.temperature <= 0.0:
                a, emitted = accept_greedy(ds, va[sl])
            else:
                if s.rng is None:
                    s.rng = np.random.default_rng(s.seed)
                pt = [_softmax(va[sl, j], s.temperature)
                      for j in range(len(ds) + 1)]
                a, emitted = accept_sample(ds, dprobs[sl], pt, s.rng)
            self._n_drafted += len(ds)
            self._n_accepted += a
            if ds:
                m_spec_drafted.inc(len(ds))
            if a:
                m_spec_accepted.inc(a)
            new_cursor = old + a + 1
            s.cursor = new_cursor
            new_lengths[sl] = new_cursor
            # drafter sync: positions [L0, L0 + k) were consumed this
            # round; the valid prefix stops at the last accepted position,
            # and whatever accepted tokens the drafter missed become next
            # round's catch-up feed
            L0 = s.drafter_len
            valid = min(L0 + k, new_cursor)
            hist = pend0[sl] + [nxt] + list(ds[:a])
            s.drafter_pending = hist[valid - L0:new_cursor - L0]
            s.drafter_len = valid
            dlen[sl] = valid
            finished = False
            for tok in emitted:
                s.next_token = tok
                self._n_spec_emitted += 1
                if self._emit_token(s, tok):
                    finished = True
                    break
            if finished:
                self._retire(s, "eos" if self.eos_id is not None
                             and s.next_token == self.eos_id else "length")
        self._caches = paged_rewind_slots(self._caches, new_lengths)
        self._drafter.set_lengths(dlen)
        return True

    def _decode_once(self) -> bool:
        """One batched decode iteration over every DECODE slot."""
        import jax.numpy as jnp
        import numpy as np

        toks = np.zeros(self.slots, np.int32)
        active = np.zeros(self.slots, np.int32)
        live: List[_Seq] = []
        for i, seq in enumerate(self._slot_seqs):
            if seq is None or seq.state != _DECODE:
                continue
            if seq.cancelled:
                self._retire(seq, "cancelled")
                continue
            if self._paged and not self._ensure_pages(seq, seq.cursor + 1):
                continue  # this sequence failed cleanly; others continue
            toks[i] = seq.next_token
            active[i] = 1
            live.append(seq)
        if not live:
            return False
        t0 = flight.now()
        if self._paged:
            logits, self._caches = self._step(
                self.params, jnp.asarray(toks), jnp.asarray(active),
                jnp.asarray(self._read_tables),
                jnp.asarray(self._write_tables), self._caches)
        else:
            logits, self._caches = self._step(
                self.params, jnp.asarray(toks), jnp.asarray(active),
                self._caches)
        la = np.asarray(logits)
        flight.span_since(_F_DECODE, t0)
        self._record_attn(t0, 1, self.slots)
        self._n_steps += 1
        _m_steps.inc()
        self._max_active_slots = max(self._max_active_slots, len(live))
        for seq in live:
            seq.cursor += 1
            tok = self._sample(seq, la[seq.slot])
            if self._emit_token(seq, tok):
                self._retire(seq, "eos" if self.eos_id is not None
                             and tok == self.eos_id else "length")
            else:
                seq.next_token = tok
        return True

    def _run(self) -> None:
        try:
            while True:
                with self._lock:
                    if self._closed:
                        break
                if self._paged:
                    self._process_commands()
                    self._finish_migrations()
                    self._start_migrations()
                self._admit()
                did = self._prefill_one()
                if self._drafter is not None:
                    did = self._decode_spec() or did
                else:
                    did = self._decode_once() or did
                _m_active.set(float(sum(
                    1 for s in self._slot_seqs if s is not None)))
                if not did:
                    with self._lock:
                        idle = (not self._pending and not self._commands
                                and not self._migrating and all(
                                    s is None or s.cancelled
                                    for s in self._slot_seqs))
                        if idle:
                            self._wake.clear()
                    self._wake.wait(timeout=1.0)
        except BaseException as e:  # noqa: BLE001 — crosses to consumers
            self._error = e
            with self._lock:
                self._closed = True
            for seq in list(self._slot_seqs):
                if seq is not None:
                    self._fail(seq, f"{type(e).__name__}: {e}")
            with self._lock:
                pending = list(self._pending)
                self._pending.clear()
            for seq in pending:
                self._fail(seq, f"{type(e).__name__}: {e}")
            for seq in list(self._migrating):
                self._fail(seq, f"{type(e).__name__}: {e}")
            self._migrating.clear()
            self._drain_commands("scheduler crashed")
        finally:
            with self._lock:
                self._closed = True
            _m_active.set(0.0)

    # --------------------------------------------------------- lifecycle

    def _drain_commands(self, msg: str) -> None:
        """Unblock every RPC thread waiting in ``export_prefix`` with an
        error — a peer's pull degrades to its cold prefill."""
        while self._commands:
            try:
                _tokens, box, done = self._commands.popleft()
            except IndexError:
                return
            box["error"] = msg
            done.set()

    def shutdown(self, timeout_s: float = 5.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pending = list(self._pending)
            self._pending.clear()
        self._wake.set()
        self._thread.join(timeout=timeout_s)
        for seq in pending:
            self._fail(seq, "scheduler shut down")
        for seq in list(self._slot_seqs):
            if seq is not None:
                self._fail(seq, "scheduler shut down")
        for seq in list(self._migrating):
            self._fail(seq, "scheduler shut down")
        self._migrating.clear()
        self._drain_commands("scheduler shut down")
        if self._mig_thread is not None:
            self._mig_requests.put(None)
        if self._radix is not None:
            # every slot ref is gone; drain the cache so the page gauge
            # returns to zero (chaos_soak asserts this after a kill)
            self._radix.clear()

    @property
    def closed(self) -> bool:
        return self._closed

    def compiled_programs(self) -> int:
        """Total compiled program count across the scheduler's jitted
        entry points — the two-compiles contract says this is exactly 2
        (one prefill shape + one decode shape) no matter how lengths,
        pages and prefix hits churn; speculative decoding adds the verify
        program as the only new shape (and the plain decode step, never
        driven in spec mode, stays uncompiled — the total remains 2; the
        drafter's own programs are reported separately in stats)."""
        n = self._prefill._cache_size() + self._step._cache_size()
        if self._verify is not None:
            n += self._verify._cache_size()
        return int(n)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            q = len(self._pending)
        out = {
            "mode": "continuous",
            "kv_layout": self.kv_layout,
            "slots": self.slots,
            "prefill_chunk": self.prefill_chunk,
            "arena_len": self.arena_len,
            "decode_steps": self._n_steps,
            "prefill_chunks": self._n_prefill_chunks,
            "admitted": self._n_admitted,
            "retired": self._n_retired,
            "tokens_generated": self._n_tokens,
            # iteration-level proof signals: > 0 means a request was
            # admitted while others were mid-generation, which a
            # flush-and-drain batcher can never do
            "admitted_mid_flight": self._admitted_mid_flight,
            "max_active_slots": self._max_active_slots,
            "peak_queue_depth": self._peak_queue_depth,
            "queue_depth": q,
            "active_slots": sum(1 for s in self._slot_seqs if s is not None),
            "compiled_programs": self.compiled_programs(),
        }
        if self._paged:
            out["page_tokens"] = self.page_tokens
            out["pages_per_slot"] = self._pages_per_slot
            out["attn_lane"] = self.attn_lane
            out["attn_bytes_moved"] = self._n_attn_bytes
            out.update(self._arena.stats())
            if self._radix is not None:
                out.update(self._radix.stats())
                out["prefix_hit_tokens"] = self._n_prefix_hit_tokens
                out["migrations"] = self._n_migrations
                out["migrated_pages"] = self._n_migrated_pages
                out["migration_failures"] = self._n_migration_failures
                out["migrations_pending"] = len(self._migrating)
        if self._drafter is not None:
            out["spec_k"] = self.spec_k
            out["drafter"] = self._drafter.name
            out["spec_rounds"] = self._n_spec_rounds
            out["spec_drafted_tokens"] = self._n_drafted
            out["spec_accepted_tokens"] = self._n_accepted
            out["spec_accept_rate"] = (
                self._n_accepted / self._n_drafted
                if self._n_drafted else 0.0)
            out["spec_tokens_per_step"] = (
                self._n_spec_emitted / self._n_spec_rounds
                if self._n_spec_rounds else 0.0)
            out["drafter_compiled_programs"] = (
                self._drafter.compiled_programs())
        return out
