"""Continuous (iteration-level) batching scheduler for LLM serve replicas.

Replaces the flush-and-drain loop of ``@serve.batch`` for the LLM path
(ISSUE 9, ROADMAP item 4): instead of admitting a request batch, running
prefill plus the ENTIRE ``max_new_tokens`` decode loop, and only then
looking at the queue again, the scheduler owns a slotted KV-cache arena of
``slots`` sequence slots (``models.decode.SlotKVCache``) and drives ONE
fixed-shape jitted decode step over the whole arena per iteration:

  * new requests are admitted into free slots *between* decode iterations
    and prefilled in ``prefill_chunk``-token chunks (one chunk per
    iteration), so a long prompt can never stall in-flight decodes;
  * finished / EOS / cancelled sequences retire their slot immediately —
    the freed slot is re-admitted on the very next iteration;
  * every sampled token streams out to its request's asyncio queue the
    iteration it is produced, so streaming and non-streaming consumers ride
    the same batched program (no per-stream single-sequence decode loops).

This is the serving analog of PR 8's 1F1B pipeline loop: the device-side
program shape is compiled once and the host-side loop only decides *which*
sequences occupy which slots. All jax work runs on the scheduler's own
thread — the replica's asyncio event loop only ever touches queues.

ISSUE 13 rebuilds the arena as a PAGED pool (``kv_layout="paged"``, the
default): KV storage is a pool of ``page_tokens``-sized pages
(``models.decode.PagedKVCache``), each slot owns a page table instead of a
contiguous worst-case ``arena_len`` range, and the same two compiled
programs gather/scatter through the tables at fixed shapes — so long/idle
sequences stop reserving memory they never use and a replica admits far
more concurrent sequences at the same arena bytes. On top of paging a
PREFIX/RADIX CACHE (``serve/_private/paging.RadixCache``) makes admitting
a request whose prompt shares a cached prefix a page-table splice + cursor
jump instead of a re-prefill; eviction is LRU over refcount-0 nodes under
arena pressure. ``kv_layout="contiguous"`` keeps the PR-9 arena as the
measured baseline (the collective layer's ``algo="kv"`` idiom).

Knobs: ``RAY_TPU_SERVE_SLOTS`` (arena width), ``RAY_TPU_SERVE_PREFILL_CHUNK``
(prefill chunk tokens), ``RAY_TPU_SERVE_KV_LAYOUT``,
``RAY_TPU_SERVE_PAGE_TOKENS``, ``RAY_TPU_SERVE_KV_PAGES`` (0 = size the
pool to the contiguous worst case), ``RAY_TPU_SERVE_PREFIX_CACHE``; all
overridable per-deployment via LLMServer init.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from functools import partial
from typing import Any, Dict, List, Optional

from ray_tpu._private import flight
from ray_tpu._private.metrics import Counter, Gauge

# flight-recorder span ids: the per-iteration admit/prefill/decode/retire
# phases the aggregate counters can't localize (per-thread ring records,
# no locks/RPCs — safe at decode-iteration rates)
_F_ADMIT = flight.intern("serve.admit")
_F_PREFILL = flight.intern("serve.prefill")
_F_DECODE = flight.intern("serve.decode")
_F_RETIRE = flight.intern("serve.retire")

_m_steps = Counter(
    "ray_tpu_serve_decode_steps_total",
    "Batched slot-arena decode iterations executed")
_m_prefill_chunks = Counter(
    "ray_tpu_serve_prefill_chunks_total",
    "Chunked prefill programs executed")
_m_tokens = Counter(
    "ray_tpu_serve_tokens_generated_total",
    "Tokens sampled and streamed out of the slot arena")
_m_admitted = Counter(
    "ray_tpu_serve_seqs_admitted_total",
    "Sequences admitted into a KV arena slot")
_m_retired = Counter(
    "ray_tpu_serve_seqs_retired_total",
    "Sequences retired from their slot (finished/EOS/cancelled/error)")
_m_active = Gauge(
    "ray_tpu_serve_slots_active",
    "KV arena slots currently holding a live sequence")
_m_queue_depth = Gauge(
    "ray_tpu_serve_queue_depth",
    "Requests waiting for a free KV arena slot")

# sequence states
_QUEUED = "queued"
_PREFILL = "prefill"
_DECODE = "decode"
_DONE = "done"


class SchedulerClosedError(RuntimeError):
    pass


class _Seq:
    """One in-flight generation request and its consumer-side queue."""

    __slots__ = ("prompt", "remaining_prompt", "max_new", "temperature",
                 "seed", "slot", "state", "n_generated", "next_token",
                 "queue", "loop", "cancelled", "t_submit", "t_first_token",
                 "rng", "cached_len", "cursor", "owned_pages", "radix_node",
                 "table_fill")

    def __init__(self, prompt: List[int], max_new: int, temperature: float,
                 seed: int, loop, queue):
        self.prompt = prompt
        self.remaining_prompt = list(prompt)
        self.max_new = max_new
        self.temperature = temperature
        self.seed = seed
        self.slot: Optional[int] = None
        self.state = _QUEUED
        self.n_generated = 0
        self.next_token: Optional[int] = None
        self.queue = queue
        self.loop = loop
        self.cancelled = False
        self.t_submit = time.monotonic()
        self.t_first_token: Optional[float] = None
        self.rng = None  # lazily created numpy Generator for temperature > 0
        # ---- paged-arena bookkeeping (host mirrors of device state) ----
        self.cached_len = 0            # spliced prefix tokens (page-aligned)
        self.cursor = 0                # mirrors the slot's device cursor
        self.owned_pages: List[int] = []  # pages this slot must free
        self.radix_node = None         # ref-counted prefix-cache node
        self.table_fill = 0            # logical pages present in the table


class ContinuousScheduler:
    """Slotted-arena continuous-batching decode scheduler.

    ``params`` are the (device-resident) model parameters shared by every
    program; the scheduler owns the KV arena and two jitted programs —
    ``prefill_into_slot`` (one compiled shape: [1, prefill_chunk]) and
    ``slot_decode_step`` ([slots]) — both with donated caches so the arena
    updates in place instead of being copied per iteration.
    """

    def __init__(self, cfg, params, *, slots: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 arena_len: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 cache_dtype=None,
                 kv_layout: Optional[str] = None,
                 page_tokens: Optional[int] = None,
                 kv_pages: Optional[int] = None,
                 prefix_cache: Optional[bool] = None):
        import numpy as np
        import jax

        from ray_tpu._private.config import global_config
        from ray_tpu.models.decode import (init_paged_caches,
                                           init_slot_caches,
                                           paged_decode_step,
                                           paged_prefill_into_slot,
                                           prefill_into_slot,
                                           slot_decode_step)

        conf = global_config()
        self.cfg = cfg
        self.params = params
        # `is None` (not `or`): an explicit 0 must hit the validation
        # below, not silently take the config default (the PR-8 depth=0
        # lesson)
        self.slots = int(conf.serve_slots if slots is None else slots)
        self.prefill_chunk = int(conf.serve_prefill_chunk
                                 if prefill_chunk is None else prefill_chunk)
        self.arena_len = int(cfg.max_seq_len if arena_len is None
                             else arena_len)
        self.eos_id = eos_id
        self.kv_layout = (conf.serve_kv_layout if kv_layout is None
                          else kv_layout)
        if self.kv_layout not in ("paged", "contiguous"):
            raise ValueError(
                f"kv_layout must be 'paged' or 'contiguous', got "
                f"{self.kv_layout!r}")
        self._paged = self.kv_layout == "paged"
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {self.slots}")
        if self.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {self.prefill_chunk}")
        if self.prefill_chunk > self.arena_len:
            raise ValueError(
                f"prefill_chunk ({self.prefill_chunk}) exceeds the arena "
                f"length ({self.arena_len})")
        self._jax = jax
        self._arena = None
        self._radix = None
        if self._paged:
            from ray_tpu.serve._private.paging import PageArena, RadixCache

            self.page_tokens = int(conf.serve_page_tokens
                                   if page_tokens is None else page_tokens)
            if self.page_tokens < 1:
                # explicit 0 (arg or RAY_TPU_SERVE_PAGE_TOKENS=0) raises —
                # never silently the config default through a falsy `or`
                raise ValueError(
                    f"page_tokens must be >= 1, got {self.page_tokens}")
            if self.arena_len % self.page_tokens != 0:
                raise ValueError(
                    f"arena_len ({self.arena_len}) must be a multiple of "
                    f"page_tokens ({self.page_tokens})")
            self._pages_per_slot = self.arena_len // self.page_tokens
            kvp = int(conf.serve_kv_pages if kv_pages is None else kv_pages)
            if kvp < 0:
                raise ValueError(f"kv_pages must be >= 0, got {kvp}")
            if kvp == 0:
                # auto: the contiguous worst case (every slot could fill
                # its whole logical range) + the reserved garbage page
                kvp = self.slots * self._pages_per_slot + 1
            self.num_pages = kvp
            self._arena = PageArena(self.num_pages, self.page_tokens)
            use_prefix = (conf.serve_prefix_cache if prefix_cache is None
                          else bool(prefix_cache))
            if use_prefix:
                self._radix = RadixCache(self._arena)
            # host-side page tables: logical page j of slot s lives at
            # physical page read_tables[s, j]; 0 = the garbage page
            # (unallocated reads are causally masked, redirected writes
            # are absorbed)
            self._read_tables = np.zeros(
                (self.slots, self._pages_per_slot), np.int32)
            self._write_tables = np.zeros(
                (self.slots, self._pages_per_slot), np.int32)
            # donated caches: the pool mutates in place across iterations;
            # the tables are tiny per-call host->device uploads
            self._prefill = jax.jit(partial(paged_prefill_into_slot, cfg),
                                    donate_argnums=(6,))
            self._step = jax.jit(partial(paged_decode_step, cfg),
                                 donate_argnums=(5,))
            self._caches = init_paged_caches(
                cfg, self.slots, self.num_pages, self.page_tokens,
                self._pages_per_slot, cache_dtype)
        else:
            from ray_tpu._private.config import env_flag_explicit

            env_on = env_flag_explicit("serve_prefix_cache")
            if prefix_cache or (prefix_cache is None and env_on):
                # explicit intent conflicts loudly. "Explicit" means the
                # constructor arg or the env var (parsed by the config
                # layer's own bool rule); serve_prefix_cache=True arriving
                # through config is indistinguishable from the default
                # (which documents itself as paged-layout-only), so it
                # simply does not apply to the contiguous baseline
                raise ValueError(
                    "prefix_cache requires kv_layout='paged' (the "
                    "contiguous arena has no shareable pages)")
            self.page_tokens = 0
            self._pages_per_slot = 0
            self.num_pages = 0
            # donated caches: the arena mutates in place across iterations
            self._prefill = jax.jit(partial(prefill_into_slot, cfg),
                                    donate_argnums=(4,))
            self._step = jax.jit(partial(slot_decode_step, cfg),
                                 donate_argnums=(3,))
            self._caches = init_slot_caches(cfg, self.slots, self.arena_len,
                                            cache_dtype)
        self._slot_seqs: List[Optional[_Seq]] = [None] * self.slots
        self._prefill_rr = 0  # round-robin cursor over prefilling slots
        self._pending: deque = deque()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._closed = False
        self._error: Optional[BaseException] = None
        # stats (host-side; mirrored into the process metric registry)
        self._n_steps = 0
        self._n_prefill_chunks = 0
        self._n_admitted = 0
        self._n_retired = 0
        self._n_tokens = 0
        self._n_prefix_hit_tokens = 0
        self._admitted_mid_flight = 0
        self._max_active_slots = 0
        self._peak_queue_depth = 0
        self._thread = threading.Thread(
            target=self._run, name="serve-continuous-scheduler", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- submit

    def max_prompt_len(self, max_new: int) -> int:
        """Longest admissible prompt for a given generation budget: the
        padded prefill chunks AND prompt+new tokens must fit the arena.
        Page-aware: with a paged pool smaller than one slot's worst case,
        the whole-pool page budget also caps a single sequence — an
        over-budget request is rejected loudly at submit, before any
        pages are allocated."""
        c = self.prefill_chunk
        effective = self.arena_len
        if self._paged:
            effective = min(effective,
                            self._arena.usable_pages * self.page_tokens)
        by_pad = (effective // c) * c
        return min(by_pad, effective - max_new)

    def submit(self, prompt_ids: List[int], *, max_new_tokens: int,
               temperature: float = 0.0, seed: int = 0,
               loop=None, queue=None) -> _Seq:
        """Enqueue a generation. Tokens/end/error events arrive on ``queue``
        via ``loop.call_soon_threadsafe`` as ``("tok", id)``, ``("end",
        reason)`` or ``("err", message)`` tuples. Thread/loop-safe."""
        if not prompt_ids:
            raise ValueError("prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt_ids) > self.max_prompt_len(max_new_tokens):
            raise ValueError(
                f"prompt of {len(prompt_ids)} tokens + {max_new_tokens} new "
                f"tokens does not fit a {self.arena_len}-token arena slot "
                f"(prefill pads prompts to {self.prefill_chunk}-token "
                f"chunks)")
        seq = _Seq(list(prompt_ids), max_new_tokens, temperature, seed,
                   loop, queue)
        with self._lock:
            if self._closed:
                raise SchedulerClosedError(
                    "scheduler is shut down" if self._error is None
                    else f"scheduler failed: {self._error!r}")
            self._pending.append(seq)
            self._peak_queue_depth = max(self._peak_queue_depth,
                                         len(self._pending))
            _m_queue_depth.set(float(len(self._pending)))
        self._wake.set()
        return seq

    def cancel(self, seq: _Seq) -> None:
        """Mark a sequence cancelled; its slot retires on the next
        iteration (pending sequences are dropped at admission)."""
        seq.cancelled = True
        self._wake.set()

    # -------------------------------------------------------------- loop

    def _emit(self, seq: _Seq, item) -> None:
        if seq.loop is None or seq.queue is None:
            return
        try:
            seq.loop.call_soon_threadsafe(seq.queue.put_nowait, item)
        except RuntimeError:
            # consumer's loop is gone — nobody is listening; retire quietly
            seq.cancelled = True

    def _release_slot_resources(self, seq: _Seq) -> None:
        """Paged-arena teardown for one slot: drop the prefix-cache ref,
        free owned pages, and zero the page-table rows (so an inactive
        slot's decode gather/scatter touches only the garbage page)."""
        if not self._paged or seq.slot is None:
            return
        slot = seq.slot
        if seq.radix_node is not None:
            self._radix.release(seq.radix_node)
            seq.radix_node = None
        if seq.owned_pages:
            self._arena.free(seq.owned_pages)
            seq.owned_pages = []
        seq.table_fill = 0
        self._read_tables[slot, :] = 0
        self._write_tables[slot, :] = 0

    def _retire(self, seq: _Seq, reason: str) -> None:
        self._release_slot_resources(seq)
        if seq.slot is not None:
            flight.instant(_F_RETIRE, seq.slot)
            self._slot_seqs[seq.slot] = None
            seq.slot = None
        seq.state = _DONE
        self._n_retired += 1
        _m_retired.inc()
        self._emit(seq, ("end", reason))

    def _fail(self, seq: _Seq, msg: str) -> None:
        self._release_slot_resources(seq)
        if seq.slot is not None:
            self._slot_seqs[seq.slot] = None
            seq.slot = None
        seq.state = _DONE
        self._n_retired += 1
        _m_retired.inc()
        self._emit(seq, ("err", msg))

    def _ensure_pages(self, seq: _Seq, upto: int) -> bool:
        """Grow the slot's page table so its logical view covers
        [0, upto) tokens, evicting LRU unreferenced prefix-cache nodes
        under pressure. On exhaustion the SEQUENCE fails cleanly (the
        scheduler and its other slots keep running). Returns True if the
        pages are present."""
        from ray_tpu.serve._private.paging import OutOfPagesError

        need = -(-upto // self.page_tokens)
        missing = need - seq.table_fill
        if missing <= 0:
            return True
        try:
            pages = self._arena.alloc(missing)
        except OutOfPagesError:
            if self._radix is not None:
                self._radix.evict(missing - self._arena.free_pages)
            try:
                pages = self._arena.alloc(missing)
            except OutOfPagesError:
                self._fail(seq, f"kv arena out of pages (need {missing} "
                                f"more, {self._arena.free_pages} free of "
                                f"{self._arena.usable_pages}; nothing "
                                f"evictable)")
                return False
        slot = seq.slot
        for j, p in enumerate(pages, start=seq.table_fill):
            self._read_tables[slot, j] = p
            self._write_tables[slot, j] = p
        seq.owned_pages.extend(pages)
        seq.table_fill = need
        return True

    def _sample(self, seq: _Seq, logits_row) -> int:
        import numpy as np

        if seq.temperature <= 0.0:
            return int(np.asarray(logits_row).argmax())
        if seq.rng is None:
            seq.rng = np.random.default_rng(seq.seed)
        x = np.asarray(logits_row, np.float64) / seq.temperature
        x -= x.max()
        p = np.exp(x)
        p /= p.sum()
        return int(seq.rng.choice(len(p), p=p))

    def _emit_token(self, seq: _Seq, tok: int) -> bool:
        """Record + stream one sampled token; returns True if the sequence
        is finished (budget exhausted or EOS)."""
        seq.n_generated += 1
        self._n_tokens += 1
        _m_tokens.inc()
        if seq.t_first_token is None:
            seq.t_first_token = time.monotonic()
        self._emit(seq, ("tok", tok))
        if self.eos_id is not None and tok == self.eos_id:
            return True
        return seq.n_generated >= seq.max_new

    def _splice_prefix(self, seq: _Seq) -> None:
        """Prefix-cache lookup at admission: splice the longest cached
        page-aligned prefix of the prompt into the slot's read table
        (write entries stay on the garbage page — shared pages are
        immutable) and jump the cursor past it. The last prompt token is
        never matched: it must re-prefill to produce the first sampled
        token's logits. The splice is clamped so the remaining tail's
        padded chunks still fit the logical view (chunks restart at the
        cursor, which is page- but not chunk-aligned)."""
        pages, matched, node = self._radix.match(seq.prompt[:-1])
        if matched == 0:
            self._radix.note_miss()
            return
        T, C = self.page_tokens, self.prefill_chunk
        keep = matched
        while keep > 0:
            rem = len(seq.prompt) - keep
            if keep + (-(-rem // C)) * C <= self.arena_len:
                break
            keep -= T
        if keep <= 0:
            # the whole match was clamped away — nothing avoided, so this
            # is a MISS for the hit-rate metrics
            self._radix.release(node)
            self._radix.note_miss()
            return
        self._radix.note_hit(keep)
        n = keep // T
        self._read_tables[seq.slot, :n] = pages[:n]
        seq.cached_len = keep
        seq.table_fill = n
        seq.radix_node = node
        self._n_prefix_hit_tokens += keep

    def _admit(self) -> None:
        from ray_tpu.models.decode import paged_reset_slot, reset_slot

        while True:
            with self._lock:
                if not self._pending:
                    break
                free = next((i for i, s in enumerate(self._slot_seqs)
                             if s is None), None)
                if free is None:
                    break
                seq = self._pending.popleft()
                _m_queue_depth.set(float(len(self._pending)))
            if seq.cancelled:
                self._retire(seq, "cancelled")
                continue
            in_flight = any(s is not None for s in self._slot_seqs)
            seq.slot = free
            seq.state = _PREFILL
            self._slot_seqs[free] = seq
            if self._paged:
                seq.cached_len = 0
                seq.owned_pages = []
                seq.radix_node = None
                seq.table_fill = 0
                self._read_tables[free, :] = 0
                self._write_tables[free, :] = 0
                if self._radix is not None:
                    self._splice_prefix(seq)
                seq.cursor = seq.cached_len
                seq.remaining_prompt = seq.prompt[seq.cached_len:]
                self._caches = paged_reset_slot(self._caches, free,
                                                seq.cached_len)
            else:
                self._caches = reset_slot(self._caches, free)
            self._n_admitted += 1
            flight.instant(_F_ADMIT, free)
            _m_admitted.inc()
            if in_flight:
                # the signal request-level flush-and-drain cannot produce:
                # an admission while other sequences are mid-generation
                self._admitted_mid_flight += 1

    def _prefill_one(self) -> bool:
        """Advance ONE prefilling sequence by one chunk, round-robin over
        slots — concurrent prompts interleave their chunks, so one long
        prompt cannot monopolize prefill (and decode never waits more than
        one chunk). Returns True if a chunk ran."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        start = self._prefill_rr
        for off in range(self.slots):
            i = (start + off) % self.slots
            seq = self._slot_seqs[i]
            if seq is None or seq.state != _PREFILL:
                continue
            self._prefill_rr = (i + 1) % self.slots
            if seq.cancelled:
                self._retire(seq, "cancelled")
                continue
            # pages are needed only up to the REAL tokens this chunk
            # writes — pad positions beyond them land on unallocated
            # table entries, which the garbage-page write redirect
            # absorbs by design (don't fail a fitting sequence for
            # pad-only pages when the pool is tight)
            if self._paged and not self._ensure_pages(
                    seq, seq.cursor + min(len(seq.remaining_prompt),
                                          self.prefill_chunk)):
                continue  # failed cleanly; other slots keep running
            chunk = seq.remaining_prompt[:self.prefill_chunk]
            seq.remaining_prompt = seq.remaining_prompt[self.prefill_chunk:]
            real = len(chunk)
            padded = chunk + [0] * (self.prefill_chunk - real)
            tokens = jnp.asarray([padded], jnp.int32)
            t0 = flight.now()
            if self._paged:
                logits, self._caches = self._prefill(
                    self.params, tokens, np.int32(real), np.int32(seq.slot),
                    jnp.asarray(self._read_tables[seq.slot]),
                    jnp.asarray(self._write_tables[seq.slot]),
                    self._caches)
                seq.cursor += real
            else:
                logits, self._caches = self._prefill(
                    self.params, tokens, np.int32(real), np.int32(seq.slot),
                    self._caches)
            if t0:
                # jax dispatch is async: without a sync the span would
                # time the DISPATCH and smear the real prefill compute
                # into the next decode region (the decode span gets its
                # sync from the np.asarray below)
                jax.block_until_ready(logits)
            flight.span_since(_F_PREFILL, t0)
            self._n_prefill_chunks += 1
            _m_prefill_chunks.inc()
            if self._paged and self._radix is not None \
                    and not seq.remaining_prompt:
                self._offer_prompt_pages(seq)
            if not seq.remaining_prompt:
                # prompt fully resident: sample the first token NOW — this
                # is the time-to-first-token moment
                tok = self._sample(seq, logits)
                seq.state = _DECODE
                if self._emit_token(seq, tok):
                    self._retire(seq, "length" if self.eos_id is None
                                 or tok != self.eos_id else "eos")
                else:
                    seq.next_token = tok
            return True
        return False

    def _offer_prompt_pages(self, seq: _Seq) -> None:
        """Prompt fully resident: offer its full pages to the radix cache
        so a later admit with the same prefix splices instead of
        re-prefilling. Pages the tree adopts become shared read-only
        (write-table entries redirect to the garbage page — they are
        never written again anyway: pads and decode tokens land at
        positions >= the prompt length, i.e. in later pages); spans
        another sequence cached first stay slot-owned duplicates. The
        slot swaps its admission-time node ref for the deeper inserted
        node, which pins the whole path against eviction while it
        decodes."""
        T = self.page_tokens
        ins_len = (len(seq.prompt) // T) * T
        if ins_len <= seq.cached_len:
            return
        n = ins_len // T
        slot = seq.slot
        offered = [int(x) for x in self._read_tables[slot, :n]]
        dups, node = self._radix.insert(seq.prompt[:ins_len], offered)
        adopted = set(offered) - set(dups)
        if adopted:
            seq.owned_pages = [p for p in seq.owned_pages
                               if p not in adopted]
            for j in range(n):
                if int(self._write_tables[slot, j]) in adopted:
                    self._write_tables[slot, j] = 0
        if node is not None:
            if seq.radix_node is not None:
                self._radix.release(seq.radix_node)
            seq.radix_node = node

    def _decode_once(self) -> bool:
        """One batched decode iteration over every DECODE slot."""
        import jax.numpy as jnp
        import numpy as np

        toks = np.zeros(self.slots, np.int32)
        active = np.zeros(self.slots, np.int32)
        live: List[_Seq] = []
        for i, seq in enumerate(self._slot_seqs):
            if seq is None or seq.state != _DECODE:
                continue
            if seq.cancelled:
                self._retire(seq, "cancelled")
                continue
            if self._paged and not self._ensure_pages(seq, seq.cursor + 1):
                continue  # this sequence failed cleanly; others continue
            toks[i] = seq.next_token
            active[i] = 1
            live.append(seq)
        if not live:
            return False
        t0 = flight.now()
        if self._paged:
            logits, self._caches = self._step(
                self.params, jnp.asarray(toks), jnp.asarray(active),
                jnp.asarray(self._read_tables),
                jnp.asarray(self._write_tables), self._caches)
        else:
            logits, self._caches = self._step(
                self.params, jnp.asarray(toks), jnp.asarray(active),
                self._caches)
        la = np.asarray(logits)
        flight.span_since(_F_DECODE, t0)
        self._n_steps += 1
        _m_steps.inc()
        self._max_active_slots = max(self._max_active_slots, len(live))
        for seq in live:
            seq.cursor += 1
            tok = self._sample(seq, la[seq.slot])
            if self._emit_token(seq, tok):
                self._retire(seq, "eos" if self.eos_id is not None
                             and tok == self.eos_id else "length")
            else:
                seq.next_token = tok
        return True

    def _run(self) -> None:
        try:
            while True:
                with self._lock:
                    if self._closed:
                        break
                self._admit()
                did = self._prefill_one()
                did = self._decode_once() or did
                _m_active.set(float(sum(
                    1 for s in self._slot_seqs if s is not None)))
                if not did:
                    with self._lock:
                        idle = not self._pending and all(
                            s is None or s.cancelled
                            for s in self._slot_seqs)
                        if idle:
                            self._wake.clear()
                    self._wake.wait(timeout=1.0)
        except BaseException as e:  # noqa: BLE001 — crosses to consumers
            self._error = e
            with self._lock:
                self._closed = True
            for seq in list(self._slot_seqs):
                if seq is not None:
                    self._fail(seq, f"{type(e).__name__}: {e}")
            with self._lock:
                pending = list(self._pending)
                self._pending.clear()
            for seq in pending:
                self._fail(seq, f"{type(e).__name__}: {e}")
        finally:
            with self._lock:
                self._closed = True
            _m_active.set(0.0)

    # --------------------------------------------------------- lifecycle

    def shutdown(self, timeout_s: float = 5.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pending = list(self._pending)
            self._pending.clear()
        self._wake.set()
        self._thread.join(timeout=timeout_s)
        for seq in pending:
            self._fail(seq, "scheduler shut down")
        for seq in list(self._slot_seqs):
            if seq is not None:
                self._fail(seq, "scheduler shut down")
        if self._radix is not None:
            # every slot ref is gone; drain the cache so the page gauge
            # returns to zero (chaos_soak asserts this after a kill)
            self._radix.clear()

    @property
    def closed(self) -> bool:
        return self._closed

    def compiled_programs(self) -> int:
        """Total compiled program count across the scheduler's two jitted
        entry points — the two-compiles contract says this is exactly 2
        (one prefill shape + one decode shape) no matter how lengths,
        pages and prefix hits churn."""
        return int(self._prefill._cache_size() + self._step._cache_size())

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            q = len(self._pending)
        out = {
            "mode": "continuous",
            "kv_layout": self.kv_layout,
            "slots": self.slots,
            "prefill_chunk": self.prefill_chunk,
            "arena_len": self.arena_len,
            "decode_steps": self._n_steps,
            "prefill_chunks": self._n_prefill_chunks,
            "admitted": self._n_admitted,
            "retired": self._n_retired,
            "tokens_generated": self._n_tokens,
            # iteration-level proof signals: > 0 means a request was
            # admitted while others were mid-generation, which a
            # flush-and-drain batcher can never do
            "admitted_mid_flight": self._admitted_mid_flight,
            "max_active_slots": self._max_active_slots,
            "peak_queue_depth": self._peak_queue_depth,
            "queue_depth": q,
            "active_slots": sum(1 for s in self._slot_seqs if s is not None),
            "compiled_programs": self.compiled_programs(),
        }
        if self._paged:
            out["page_tokens"] = self.page_tokens
            out["pages_per_slot"] = self._pages_per_slot
            out.update(self._arena.stats())
            if self._radix is not None:
                out.update(self._radix.stats())
                out["prefix_hit_tokens"] = self._n_prefix_hit_tokens
        return out
