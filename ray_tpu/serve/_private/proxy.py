"""HTTP ingress proxy.

Analog of `ray.serve._private.proxy.ProxyActor/HTTPProxy`
(`python/ray/serve/_private/proxy.py:1112,748`, proxy_request `:424`),
with aiohttp in place of uvicorn (not in this image): an async actor runs
the server on its actor event loop; requests route by longest matching
route prefix to the app's ingress deployment handle and flow through the
same power-of-two router as Python-side calls.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)


class ProxyActor:
    def __init__(self, controller, port: int):
        self._controller = controller
        self._port = port
        self._routes: Dict[str, Any] = {}
        self._route_asgi: Dict[str, bool] = {}  # target -> ASGI ingress
        self._handles: Dict[str, Any] = {}
        self._runner = None
        self._started_evt = asyncio.Event()
        self._start_error: Optional[str] = None

    async def ready(self) -> int:
        await self._start()
        return self._port

    async def _start(self):
        if self._runner is not None:
            # a concurrent first caller may still be mid-bind: wait until
            # the real port is known before reporting it
            await self._started_evt.wait()
            if self._start_error:
                raise RuntimeError(self._start_error)
            return
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app, access_log=None)
        try:
            await self._runner.setup()
            site = web.TCPSite(self._runner, "0.0.0.0", self._port)
            await site.start()
        except BaseException as e:
            # a failed bind (port in use) must not wedge future ready()
            # calls behind a never-set event
            self._runner = None
            self._start_error = f"proxy bind failed: {e}"
            self._started_evt.set()
            self._started_evt = asyncio.Event()  # fresh gate for retries
            raise
        if self._port == 0:
            # ephemeral bind: report the real port (tests and multi-tenant
            # hosts use port 0 to avoid collisions)
            self._port = site._server.sockets[0].getsockname()[1]
        self._start_error = None
        self._started_evt.set()
        asyncio.ensure_future(self._route_refresher())
        logger.info("serve proxy listening on :%d", self._port)

    async def _route_refresher(self):
        while True:
            await self._refresh_routes()
            await asyncio.sleep(1.0)

    async def _refresh_routes(self):
        try:
            # fetch BOTH, then assign together with no await in between:
            # assigning routes first opened a window where a request saw
            # the new route with a stale ASGI flag and took the plain
            # handle_request path into an ASGI-only deployment
            # (AttributeError: no __call__). asgi is fetched second so
            # it is at least as new as the routes it annotates.
            routes = await self._controller.get_routes.remote()
            # published by the controller from the deployment class's
            # static marker — the proxy never probes user code, and a
            # redeploy (plain <-> ASGI) takes effect on the next refresh
            route_asgi = await self._controller.get_route_asgi.remote()
            self._routes = routes
            self._route_asgi = route_asgi
        except Exception:
            pass

    async def _handle(self, request):
        from aiohttp import web

        path = "/" + request.match_info["tail"]
        if path == "/-/healthz":
            return web.Response(text="ok")
        if path == "/-/routes":
            if not self._routes:
                await self._refresh_routes()
            return web.json_response(self._routes)
        if not self._routes:
            await self._refresh_routes()
        target = None
        best = -1
        for prefix, dest in self._routes.items():
            if path.startswith(prefix) and len(prefix) > best:
                target, best = dest, len(prefix)
        if target is None:
            return web.Response(status=404, text="no route")
        handle = self._handles.get(target)
        if handle is None:
            from ray_tpu.serve.handle import DeploymentHandle

            app_name, dep = target.split("/", 1)
            handle = DeploymentHandle(app_name, dep, self._controller)
            self._handles[target] = handle
        if self._route_asgi.get(target, False):
            # ASGI deployment (serve.ingress): full scope translation,
            # streaming responses, websocket bridging (proxy.py:431)
            try:
                if request.headers.get("Upgrade", "").lower() == "websocket":
                    return await self._handle_ws(request, handle, path,
                                                 best)
                return await self._handle_asgi(request, handle, path, best)
            except Exception as e:  # noqa: BLE001 — replica/router failure
                logger.exception("asgi proxy error on %s", path)
                return web.Response(status=500,
                                    text=f"{type(e).__name__}: {e}")
        try:
            if request.can_read_body:
                body = await request.read()
                try:
                    payload = json.loads(body) if body else None
                except json.JSONDecodeError:
                    payload = body.decode()
            else:
                payload = dict(request.query) or None
            # assign_request does blocking controller lookups — keep them
            # off the proxy's event loop
            loop = asyncio.get_running_loop()
            resp = await loop.run_in_executor(
                None, lambda: handle.remote(payload))
            out = await resp
            from ray_tpu.serve.handle import STREAM_MARKER

            if isinstance(out, dict) and STREAM_MARKER in out:
                # token streaming: chunked transfer, one pull loop on the
                # replica that produced the stream (proxy.py:424 analog)
                return await self._stream_response(
                    request, resp._replica, out[STREAM_MARKER])
            if isinstance(out, (dict, list, int, float, bool)) or out is None:
                return web.json_response(out)
            if isinstance(out, bytes):
                return web.Response(body=out)
            return web.Response(text=str(out))
        except Exception as e:
            logger.exception("proxy error on %s", path)
            return web.Response(status=500, text=f"{type(e).__name__}: {e}")

    # ------------------------------------------------------------- ASGI

    def _asgi_scope(self, request, path: str, prefix_len: int,
                    ws: bool = False) -> Dict[str, Any]:
        root = path[:prefix_len].rstrip("/")
        return {
            "type": "websocket" if ws else "http",
            "asgi": {"version": "3.0", "spec_version": "2.3"},
            "http_version": "1.1",
            "method": request.method,
            "scheme": "ws" if ws else "http",
            "path": path[prefix_len:] or "/",
            "raw_path": path,
            "root_path": root,
            "query_string": request.query_string,
            # header values as str pairs on the wire; the replica-side
            # adapter re-encodes to the bytes pairs ASGI requires
            "headers": [(k.lower(), v) for k, v in request.headers.items()],
            "client": (request.remote, 0),
        }

    async def _handle_asgi(self, request, handle, path: str,
                           prefix_len: int):
        from aiohttp import web

        body = await request.read() if request.can_read_body else b""
        scope = self._asgi_scope(request, path, prefix_len)
        loop = asyncio.get_running_loop()
        sh = handle.options(stream=True)
        resp_obj = await loop.run_in_executor(
            None, lambda: sh._call("__serve_asgi__", (scope, body), {}))
        gen = resp_obj.ref  # ObjectRefGenerator of header + body chunks
        try:
            first_ref = await gen.__anext__()
        except StopAsyncIteration:
            return web.Response(status=500, text="empty ASGI response")
        head = await first_ref
        resp = web.StreamResponse(status=head.get("status", 200))
        for k, v in head.get("headers", []):
            if k.lower() not in ("content-length", "transfer-encoding"):
                resp.headers[k] = v
        resp.enable_chunked_encoding()
        await resp.prepare(request)
        try:
            async for chunk_ref in gen:
                chunk = await chunk_ref
                if isinstance(chunk, str):
                    chunk = chunk.encode()
                if chunk:
                    await resp.write(chunk)
        except Exception as e:  # noqa: BLE001 — mid-stream failure
            logger.warning("asgi stream aborted: %s", e)
        try:
            await resp.write_eof()
        except Exception:
            pass
        return resp

    async def _handle_ws(self, request, handle, path: str, prefix_len: int):
        """Websocket pass-through (≈ proxy.py:431): outbound ASGI events
        ride a streaming generator from the replica; inbound frames feed
        the session via per-message calls to the SAME replica."""
        import uuid

        from aiohttp import web

        # dispatch BEFORE upgrading: a replica/router failure here still
        # has a plain HTTP connection to answer with a 500 (after the
        # 101 upgrade there is no way to signal an error)
        sid = uuid.uuid4().hex
        scope = self._asgi_scope(request, path, prefix_len, ws=True)
        loop = asyncio.get_running_loop()
        sh = handle.options(stream=True)
        resp_obj = await loop.run_in_executor(
            None, lambda: sh._call("__serve_ws__", (sid, scope), {}))
        gen, replica = resp_obj.ref, resp_obj._replica

        ws = web.WebSocketResponse()
        await ws.prepare(request)
        # post-upgrade failures must end as close frames on THIS ws —
        # the shared 500-Response handler upstream cannot answer an
        # already-upgraded connection
        try:
            return await self._pump_ws(request, ws, gen, replica, sid)
        except Exception as e:  # noqa: BLE001 — replica died mid-session
            logger.warning("ws session %s failed: %s", sid[:8], e)
            try:
                await ws.close(code=1011)
            except Exception:
                pass
            return ws

    async def _pump_ws(self, request, ws, gen, replica, sid: str):
        async def pump_outbound():
            try:
                async for ev_ref in gen:
                    event = await ev_ref
                    et = event["type"]
                    if et == "websocket.accept":
                        continue  # aiohttp accepted at prepare()
                    if et == "websocket.send":
                        if event.get("text") is not None:
                            await ws.send_str(event["text"])
                        elif event.get("bytes") is not None:
                            await ws.send_bytes(event["bytes"])
                    elif et == "websocket.close":
                        await ws.close(code=event.get("code", 1000))
                        return
            except Exception as e:  # noqa: BLE001
                logger.warning("ws outbound pump ended: %s", e)
                try:
                    await ws.close(code=1011)
                except Exception:
                    pass

        out_task = asyncio.ensure_future(pump_outbound())

        async def feed(event):
            await replica.handle_request.remote(
                "__serve_ws_feed__", (sid, event), {})

        from aiohttp import WSMsgType

        try:
            async for msg in ws:
                if msg.type == WSMsgType.TEXT:
                    await feed({"type": "websocket.receive",
                                "text": msg.data})
                elif msg.type == WSMsgType.BINARY:
                    await feed({"type": "websocket.receive",
                                "bytes": msg.data})
                elif msg.type in (WSMsgType.CLOSE, WSMsgType.CLOSING,
                                  WSMsgType.ERROR):
                    break
        finally:
            try:
                await feed({"type": "websocket.disconnect", "code": 1000})
            except Exception:
                pass
            if not out_task.done():
                # give the app a moment to close gracefully
                try:
                    await asyncio.wait_for(out_task, timeout=5)
                except Exception:
                    out_task.cancel()
        return ws

    async def _stream_response(self, request, replica, stream_id: int):
        from aiohttp import web

        resp = web.StreamResponse(
            headers={"Content-Type": "text/plain; charset=utf-8",
                     "X-Serve-Stream": "1"})
        resp.enable_chunked_encoding()
        await resp.prepare(request)
        # once prepare() has sent 200 + headers, every failure must
        # terminate THIS response — returning a fresh 500 Response on a
        # transport mid-chunked-stream corrupts the connection
        try:
            while True:
                chunk = await replica.stream_next.remote(stream_id)
                for item in chunk["items"]:
                    if isinstance(item, bytes):
                        data = item
                    elif isinstance(item, str):
                        data = item.encode()
                    else:
                        data = (json.dumps(item) + "\n").encode()
                    await resp.write(data)
                if chunk.get("error"):
                    await resp.write(
                        f"\n[stream error: {chunk['error']}]".encode())
                    break
                if chunk["done"]:
                    break
        except Exception as e:  # noqa: BLE001 — replica died / client gone
            logger.warning("stream %d aborted: %s", stream_id, e)
            try:
                await resp.write(f"\n[stream aborted: {e}]".encode())
            except Exception:
                pass
        try:
            await resp.write_eof()
        except Exception:
            pass
        return resp
