"""Prefix-affinity routing: fleet-level cache coordination (ISSUE 18).

Each replica's RadixCache is a per-process island; this module makes the
prefix hit rate a FLEET property. Replicas advertise a compact digest of
their cache — one 64-bit CHAIN hash per page-boundary span on every
root path — through the stats the controller already polls; the router
hashes an incoming prompt's page-aligned prefix the same way and steers
it to the replica holding the deepest match.

The chain construction is what makes a single set-membership test a
full prefix comparison: the hash at page i is

    h_i = blake2b(h_{i-1} || int32(tokens of page i), digest_size=8)

so ``h_i`` commits to the ENTIRE first i pages, not just page i.
``prompt_hash[i] in replica_digest`` therefore means the replica holds a
cached span whose first i pages are token-identical to the prompt's
(modulo 64-bit collision — a false steer costs one cold prefill, never a
wrong token: affinity only picks WHERE a request runs). Digests are
maintained incrementally by the RadixCache (insert registers, evict
unregisters, splits are hash-preserving) — no tree walk on the stats
path.

Steering must never become a hotspot machine: the router abandons
affinity for pow-2 choice whenever the steered replica's inflight count
exceeds the least-loaded replica's by more than the skew bound, or the
replica carries a recent fail mark. On a fleet-hit/local-miss the router
attaches a ``_fleet_hint`` naming the holder so the chosen replica can
PULL the pages itself (never through the controller).
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

from ray_tpu._private.metrics import Counter

m_affinity_hits = Counter(
    "ray_tpu_serve_fleet_affinity_hits_total",
    "Router picks steered to a replica holding the prompt's prefix")
m_affinity_misses = Counter(
    "ray_tpu_serve_fleet_affinity_misses_total",
    "Router picks that fell back to pow-2 (no digest match, load skew, "
    "or fail-marked holder)")
m_migrations = Counter(
    "ray_tpu_serve_fleet_migrations_total",
    "Cross-replica prefix page pulls completed (spliced into the puller)")
m_migrated_pages = Counter(
    "ray_tpu_serve_fleet_migrated_pages_total",
    "KV pages copied between replicas by completed migrations")

# chain seed: the hash "before page 0". Any fixed 8 bytes works; zeros
# keep digests reproducible across processes
CHAIN_SEED = 0
_DIGEST_SIZE = 8


def extend_chain(prev: int, span: Sequence[int]) -> int:
    """One chain step: fold one page's tokens onto the running hash."""
    h = hashlib.blake2b(
        prev.to_bytes(_DIGEST_SIZE, "little")
        + b"".join(int(t).to_bytes(4, "little", signed=True) for t in span),
        digest_size=_DIGEST_SIZE)
    return int.from_bytes(h.digest(), "little")


def chain_hashes(tokens: Sequence[int], page_tokens: int,
                 seed: int = CHAIN_SEED) -> List[int]:
    """Chain hash at every page boundary of ``tokens`` (the trailing
    partial page is dropped — digests are page-aligned like the radix
    tree itself). tokens of d full pages -> [h_1 .. h_d]."""
    if page_tokens < 1:
        raise ValueError(f"page_tokens must be >= 1, got {page_tokens}")
    out: List[int] = []
    prev = seed
    full = (len(tokens) // page_tokens) * page_tokens
    for i in range(0, full, page_tokens):
        prev = extend_chain(prev, tokens[i:i + page_tokens])
        out.append(prev)
    return out


def prompt_chain(prompt_ids: Sequence[int], page_tokens: int) -> List[int]:
    """Chain hashes for the ROUTABLE prefix of a prompt. The last prompt
    token is never cached (admission matches ``prompt[:-1]`` — its KV is
    written by the sampling step), so the router must hash the same
    clipped span or it would steer on a page no replica can ever hold."""
    return chain_hashes(prompt_ids[:len(prompt_ids) - 1], page_tokens)


class AffinityIndex:
    """Router-side view of every replica's prefix digest.

    ``update`` ingests the controller's ``listen_for_digests`` payload
    (replica key -> digest dict as produced by ``RadixCache.digest``);
    ``steer`` answers the per-pick question: which replica key holds the
    deepest page-aligned match for this prompt chain, and how deep. All
    methods are cheap dict/set work — the router calls them with its
    lock held."""

    def __init__(self):
        self._sets: Dict[str, frozenset] = {}
        self._page_tokens: Optional[int] = None
        self._vocab_size: Optional[int] = None
        self._tok: str = ""
        self.version: int = -1

    def update(self, payload: Dict) -> None:
        """payload: {"version": int, "digests": {key: digest-dict}} where
        each digest-dict carries page_tokens/vocab_size/tok/hashes."""
        sets: Dict[str, frozenset] = {}
        for key, d in (payload.get("digests") or {}).items():
            if not d:
                continue
            self._page_tokens = d.get("page_tokens", self._page_tokens)
            self._vocab_size = d.get("vocab_size", self._vocab_size)
            self._tok = d.get("tok", self._tok)
            sets[key] = frozenset(d.get("hashes") or ())
        self._sets = sets
        self.version = payload.get("version", self.version)

    @property
    def page_tokens(self) -> Optional[int]:
        return self._page_tokens

    def ready(self) -> bool:
        return self._page_tokens is not None and bool(self._sets)

    def tokenize(self, prompt: str) -> Optional[List[int]]:
        """Router-side tokenization for steering. Only the byte tokenizer
        is reproducible outside the replica; requests using any other
        tokenizer must carry explicit ``prompt_ids`` to be steerable."""
        if self._tok != "byte" or self._vocab_size is None:
            return None
        v = self._vocab_size
        return [b % v for b in prompt.encode("utf-8")]

    def chain_for(self, prompt: str = "",
                  prompt_ids: Optional[Sequence[int]] = None
                  ) -> List[int]:
        if not self.ready():
            return []
        ids = list(prompt_ids) if prompt_ids is not None else (
            self.tokenize(prompt))
        if not ids:
            return []
        return prompt_chain(ids, self._page_tokens)

    def depth(self, key: str, chain: Sequence[int]) -> int:
        """Pages of ``chain`` the replica ``key`` holds (deepest i with
        chain[i-1] present — chain hashes commit to the whole prefix, so
        scanning from the deep end is exact, not heuristic)."""
        s = self._sets.get(key)
        if not s:
            return 0
        for i in range(len(chain), 0, -1):
            if chain[i - 1] in s:
                return i
        return 0

    def steer(self, chain: Sequence[int], keys: Sequence[str]
              ) -> Tuple[Optional[str], int]:
        """(holder_key, depth_pages) of the deepest match among ``keys``,
        or (None, 0) when no replica holds even one page."""
        best_key, best_depth = None, 0
        for key in keys:
            d = self.depth(key, chain)
            if d > best_depth:
                best_key, best_depth = key, d
        return best_key, best_depth

    def stats(self) -> Dict[str, int]:
        return {
            "replicas": len(self._sets),
            "hashes": sum(len(s) for s in self._sets.values()),
            "version": self.version,
        }
