"""One-copy-per-node shared model weights for serve replicas.

Every LLM replica on a node used to materialize its own full copy of the
(immutable) parameters, capping replicas-per-host and making scale-up
minutes of checkpoint staging. Here the FIRST replica on a node publishes
the host params into the node's shared-memory object arena (the PR-2
zero-copy put path — one memcpy per leaf buffer) and registers the
resulting ObjectRef in the controller KV under a (weights-key, node)
scoped entry; every LATER replica on that node ``get``s the ref and
deserializes pinned READ-ONLY numpy views over its own mmap of the same
arena range — zero additional arena bytes per replica, only pins.

The pins ride the PR-2 per-client pin accounting: a replica that dies
without unpinning has its pins reclaimed by the supervisor's dead-client
sweep, so replica churn can never leak the weights range (and the last
death lets the arena copy spill/free normally).

Cross-node delivery: replicas landing on a NEW node either pull the
global ref (chunked pipelined cross-node transfer into the local arena,
then publish locally) or — for seconds-scale scale-up without touching
the loader/checkpoint path at all — receive the tree over
``collective.broadcast`` from an existing replica
(:func:`broadcast_params`), then publish into their own node arena.
"""

from __future__ import annotations

import logging
import pickle
from typing import Any, Callable, Dict, Optional, Tuple

logger = logging.getLogger(__name__)

_KV_NS = "serve_weights"

# key -> (ObjectRef, views): holds the ref (so the owner never frees the
# object) and the views (so this process's pins persist) for the process
# lifetime. Replica death releases both through normal dead-client sweeps.
_HELD: Dict[str, Tuple[Any, Any]] = {}


def _tree_to_host(params):
    """Device pytree -> host numpy pytree (the arena-publishable form)."""
    import jax
    import numpy as np

    return jax.tree.map(np.asarray, params)


def tree_nbytes(params) -> int:
    import jax

    return sum(x.nbytes for x in jax.tree.leaves(params))


def _pack_ref(ref) -> Dict[str, Any]:
    return {"oid": ref._object_id.binary(), "owner": list(ref._owner_addr)}


def _unpack_ref(d):
    from ray_tpu._private.api import ObjectRef
    from ray_tpu._private.ids import ObjectID

    # skip_ref_counting: the publisher holds the canonical local ref in
    # _HELD; readers only pin via their views
    return ObjectRef(ObjectID(bytes(d["oid"])), tuple(d["owner"]),
                     skip_ref_counting=True)


def _cluster_ready() -> bool:
    from ray_tpu._private import api

    core = api._core
    return (core is not None and core.supervisor_addr is not None
            and core.arena is not None)


def get_or_publish(key: str, loader: Callable[[], Any], *,
                   timeout_s: float = 180.0) -> Tuple[Any, Dict[str, Any]]:
    """Return ``(host_params, info)`` with one arena copy per node.

    The first caller per node wins a KV claim, builds the params via
    ``loader()`` (or pulls another node's published copy through the
    chunked cross-node path), publishes them into the node arena, and
    registers the ref; every other caller on the node blocks on the ref
    key and attaches zero-copy. ``host_params`` is a pytree of READ-ONLY
    numpy views over the node arena for attached callers (callers
    typically ``jax.device_put`` it once into their own device memory).

    Falls back to a plain local ``loader()`` (``info["mode"] == "local"``)
    when no cluster/arena is reachable, so direct instantiation outside a
    cluster keeps working.
    """
    if not _cluster_ready():
        params = loader()
        return params, {"mode": "local", "shared": False}

    import ray_tpu
    from ray_tpu._private import api
    from ray_tpu._private import internal_kv as kv

    core = api._core
    node = core.node_id_hex or "local"
    me = core._store_client_id
    ref_key = f"ref:{key}@{node}"
    claim_key = f"claim:{key}@{node}"
    global_key = f"ref:{key}@global"

    for attempt in range(2):
        packed = kv.kv_get(ref_key, ns=_KV_NS)
        published = False
        source = "arena"
        if packed is None:
            if kv.kv_put(claim_key, me, ns=_KV_NS, overwrite=False):
                # we are this node's publisher
                try:
                    params, source = _materialize(global_key, loader,
                                                  timeout_s)
                    host = _tree_to_host(params)
                    del params
                    ref = ray_tpu.put(host)
                    del host  # the loader copy dies; the arena copy stays
                    packed = _pack_ref(ref)
                    kv.kv_put(ref_key, packed, ns=_KV_NS)
                    kv.kv_put(global_key, packed, ns=_KV_NS,
                              overwrite=False)
                    _HELD[ref_key] = (ref, None)
                    published = True
                except BaseException:
                    # release the claim so another replica can retry the
                    # election instead of deadlocking on kv_wait
                    try:
                        kv.kv_del(claim_key, ns=_KV_NS)
                    except Exception:
                        pass
                    raise
            else:
                try:
                    packed = kv.kv_wait(ref_key, timeout=timeout_s,
                                        ns=_KV_NS)
                except TimeoutError:
                    # claimed but never published (claimant died mid-load):
                    # clear the claim and re-run the election
                    try:
                        kv.kv_del(claim_key, ns=_KV_NS)
                    except Exception:
                        pass
                    continue
        ref = _unpack_ref(packed) if not published else _HELD[ref_key][0]
        try:
            views = ray_tpu.get(ref, timeout=timeout_s)
        except Exception:
            if published:
                raise
            # stale registration (the arena copy is gone — e.g. the whole
            # node restarted under the same KV): drop it and re-elect
            logger.warning("shared-weights ref %s is stale; re-electing",
                           ref_key, exc_info=True)
            for k in (ref_key, claim_key):
                try:
                    kv.kv_del(k, ns=_KV_NS)
                except Exception:
                    pass
            if attempt == 0:
                continue
            raise
        _HELD[ref_key] = (ref, views)
        info = {
            "mode": "published" if published else "attached",
            "shared": True,
            "source": source if published else "arena",
            "key": key,
            "node": node,
            "ref": ref.hex(),
            "nbytes": tree_nbytes(views),
        }
        return views, info
    raise RuntimeError(
        f"could not obtain shared weights for {key!r} within {timeout_s}s")


def _materialize(global_key: str, loader, timeout_s: float):
    """Publisher-side parameter source: prefer pulling another node's
    published copy (chunked cross-node arena transfer — no checkpoint /
    loader cost) over running the loader."""
    import ray_tpu
    from ray_tpu._private import internal_kv as kv

    packed = kv.kv_get(global_key, ns=_KV_NS)
    if packed is not None:
        try:
            return ray_tpu.get(_unpack_ref(packed),
                               timeout=timeout_s), "pull"
        except Exception:
            logger.warning("global weights ref is stale; running loader",
                           exc_info=True)
    return loader(), "loader"


def release(key: str) -> None:
    """Drop this process's hold (views + ref) on a shared-weights entry —
    for tests and explicit teardown; normal replica death releases through
    the dead-client pin sweep."""
    node = ""
    try:
        from ray_tpu._private import api

        node = api._core.node_id_hex if api._core is not None else ""
    except Exception:
        pass
    _HELD.pop(f"ref:{key}@{node or 'local'}", None)


# ------------------------------------------------------------- broadcast


def broadcast_params(params: Optional[Any], group_name: str,
                     world_size: int, rank: int, *, root: int = 0,
                     timeout_ms: int = 120_000):
    """Deliver a params pytree to new-node replicas over
    ``collective.broadcast`` (shm on one node, chunked p2p ring across
    nodes — never the controller). The root passes the tree; receivers
    pass ``None`` and get the identical tree back. The tree structure +
    leaf specs travel as a pickled uint8 header broadcast, then one
    broadcast per leaf (the transport frames carry dtype/shape, so
    receivers need no pre-sized template).

    Each participant runs in its own task/actor; the group is imperative
    and destroyed on exit, so repeated scale-ups with fresh group names
    never collide.
    """
    import jax
    import numpy as np

    from ray_tpu.util import collective as col

    col.init_collective_group(world_size, rank, backend="host",
                              group_name=group_name)
    try:
        if rank == root:
            if params is None:
                raise ValueError("broadcast root must pass the params tree")
            host = _tree_to_host(params)
            leaves, treedef = jax.tree.flatten(host)
            spec = pickle.dumps(treedef)
            col.broadcast(np.frombuffer(spec, np.uint8), src_rank=root,
                          group_name=group_name, timeout_ms=timeout_ms)
            for leaf in leaves:
                col.broadcast(np.ascontiguousarray(leaf), src_rank=root,
                              group_name=group_name, timeout_ms=timeout_ms)
            return host
        spec = col.broadcast(np.empty(0, np.uint8), src_rank=root,
                             group_name=group_name, timeout_ms=timeout_ms)
        treedef = pickle.loads(bytes(spec))
        leaves = [col.broadcast(np.empty(0, np.uint8), src_rank=root,
                                group_name=group_name,
                                timeout_ms=timeout_ms)
                  for _ in range(treedef.num_leaves)]
        return jax.tree.unflatten(treedef, leaves)
    finally:
        try:
            col.destroy_collective_group(group_name)
        except Exception:
            pass
