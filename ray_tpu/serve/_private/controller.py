"""Serve controller — the reconciling control plane.

Analog of `ray.serve._private.controller.ServeController`
(`python/ray/serve/_private/controller.py:86`, deploy_application `:719`)
+ `DeploymentStateManager` (`deployment_state.py:2309`) + the autoscaling
loop (`autoscaling_state.py`): a detached async actor that drives target
replica counts to spec, health-checks replicas, replaces dead ones, and
autoscales on in-flight request counts.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional

import ray_tpu

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"



class _DeploymentState:
    def __init__(self, app_name: str, spec: Dict[str, Any]):
        self.app_name = app_name
        self.spec = spec
        self.replicas: List[Any] = []  # actor handles
        self.version = 0
        self.target = spec["num_replicas"]
        self.status = "UPDATING"
        self.deleted = False
        # serializes scale operations: delete (scale→0) racing the
        # reconcile loop (scale→target) would otherwise livelock,
        # alternately killing and recreating the same replica
        self.lock = asyncio.Lock()

    @property
    def name(self) -> str:
        return self.spec["name"]


class ServeController:
    """Async actor; all methods run on one asyncio loop (max_concurrency
    set high by the deployer) so state mutations are single-threaded."""

    def __init__(self):
        self._apps: Dict[str, Dict[str, _DeploymentState]] = {}
        self._routes: Dict[str, str] = {}  # route_prefix -> "app/ingress"
        self._shutdown = False
        self._loop_task = None

    async def _ensure_loop(self):
        if self._loop_task is None:
            self._loop_task = asyncio.ensure_future(self._reconcile_loop())

    # ------------------------------------------------------------ deploy

    async def deploy_application(self, app_name: str,
                                 deployment_specs: List[Dict[str, Any]],
                                 route_prefix: Optional[str],
                                 ingress_name: str) -> None:
        await self._ensure_loop()
        app = self._apps.setdefault(app_name, {})
        new_names = {s["name"] for s in deployment_specs}
        # remove deployments dropped from the app
        for name in list(app):
            if name not in new_names:
                app[name].deleted = True
                await self._scale_to(app[name], 0)
                del app[name]
        for spec in deployment_specs:
            if name_state := app.get(spec["name"]):
                name_state.spec = spec
                name_state.target = spec["num_replicas"]
                name_state.version += 1
            else:
                app[spec["name"]] = _DeploymentState(app_name, spec)
        if route_prefix:
            self._routes[route_prefix] = f"{app_name}/{ingress_name}"
        await self._reconcile_once()

    async def delete_application(self, app_name: str) -> None:
        app = self._apps.pop(app_name, None)
        if app:
            for st in app.values():
                st.deleted = True
                await self._scale_to(st, 0)
        self._routes = {r: t for r, t in self._routes.items()
                        if not t.startswith(app_name + "/")}

    # --------------------------------------------------------- reconcile

    async def _reconcile_loop(self):
        while not self._shutdown:
            try:
                await self._reconcile_once()
                await self._autoscale()
            except Exception:
                logger.exception("reconcile error")
            await asyncio.sleep(0.5)

    async def _reconcile_once(self):
        for app in list(self._apps.values()):
            for st in list(app.values()):
                if st.deleted:
                    continue
                await self._health_sweep(st)
                await self._scale_to(st, st.target)
                st.status = "RUNNING" if len(st.replicas) == st.target \
                    else "UPDATING"

    async def _health_sweep(self, st: _DeploymentState):
        # Probe a snapshot, then REMOVE the dead under the lock. Never
        # assign the snapshot back: a concurrent scale-down could have
        # popped a replica mid-probe, and re-assigning would resurrect it.
        snapshot = list(st.replicas)
        dead = []
        for r in snapshot:
            try:
                ok = await asyncio.wait_for(
                    r.check_health.remote(), timeout=5)
                if not ok:
                    dead.append(r)
            except Exception:
                logger.warning("replica of %s failed health check; replacing",
                               st.name)
                dead.append(r)
        if dead:
            async with st.lock:
                before = len(st.replicas)
                st.replicas = [r for r in st.replicas if r not in dead]
                if len(st.replicas) != before:
                    st.version += 1

    async def _scale_to(self, st: _DeploymentState, n: int):
        from ray_tpu.serve._private.replica import ReplicaActor

        async with st.lock:
            await self._scale_to_locked(st, n, ReplicaActor)

    async def _scale_to_locked(self, st, n, ReplicaActor):
        while len(st.replicas) > n:
            r = st.replicas.pop()
            st.version += 1
            try:
                await r.prepare_for_shutdown.remote()
            except Exception:
                pass
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        spec = st.spec
        while len(st.replicas) < n:
            actor_opts = dict(spec.get("ray_actor_options") or {})
            actor_opts.setdefault("num_cpus", 0.1)
            handle = ray_tpu.remote(ReplicaActor).options(
                max_concurrency=spec.get("max_ongoing_requests", 8),
                **actor_opts,
            ).remote(st.app_name, st.name, spec["callable_factory"],
                     spec.get("init_args", ()), spec.get("init_kwargs", {}))
            if spec.get("user_config") is not None:
                await handle.reconfigure.remote(spec["user_config"])
            st.replicas.append(handle)
            st.version += 1

    async def _autoscale(self):
        for app in self._apps.values():
            for st in app.values():
                cfg = st.spec.get("autoscaling_config")
                if not cfg:
                    continue
                stats = []
                for r in st.replicas:
                    try:
                        stats.append(await asyncio.wait_for(
                            r.stats.remote(), timeout=5))
                    except Exception:
                        pass
                if not stats:
                    continue
                total_ongoing = sum(s["ongoing"] for s in stats)
                target_per = cfg.get("target_ongoing_requests", 2)
                desired = max(
                    cfg.get("min_replicas", 1),
                    min(cfg.get("max_replicas", 1),
                        -(-total_ongoing // target_per) or
                        cfg.get("min_replicas", 1)))
                if desired != st.target:
                    logger.info("autoscale %s: %d -> %d (ongoing=%d)",
                                st.name, st.target, desired, total_ongoing)
                    st.target = desired

    # ------------------------------------------------------------- query

    async def get_replicas(self, app_name: str, deployment_name: str):
        st = self._apps.get(app_name, {}).get(deployment_name)
        if st is None:
            return {"version": -1, "replicas": []}
        return {"version": st.version, "replicas": list(st.replicas),
                "max_ongoing": st.spec.get("max_ongoing_requests", 8)}

    async def get_routes(self) -> Dict[str, str]:
        return dict(self._routes)

    async def status(self) -> Dict[str, Any]:
        out = {}
        for app_name, app in self._apps.items():
            out[app_name] = {
                name: {"status": st.status, "replicas": len(st.replicas),
                       "target": st.target, "version": st.version}
                for name, st in app.items()
            }
        return out

    async def graceful_shutdown(self) -> None:
        self._shutdown = True
        for app_name in list(self._apps):
            await self.delete_application(app_name)
