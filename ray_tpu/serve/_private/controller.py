"""Serve controller — the reconciling control plane.

Analog of `ray.serve._private.controller.ServeController`
(`python/ray/serve/_private/controller.py:86`, deploy_application `:719`)
+ `DeploymentStateManager` (`deployment_state.py:2309`) + the autoscaling
loop (`autoscaling_state.py`): a detached async actor that drives target
replica counts to spec, health-checks replicas, replaces dead ones, and
autoscales on in-flight request counts.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional

import ray_tpu

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"



class _ReplicaHolder:
    """One replica plus its lifecycle state (≈ DeploymentReplica in
    deployment_state.py: STARTING until the first successful health probe,
    then RUNNING). A STARTING replica is only killed after
    INIT_TIMEOUT_S — model replicas legitimately take many seconds to
    construct (worker spawn + framework import + weight init/load), and
    probing them with the steady-state timeout would replace them forever."""

    INIT_TIMEOUT_S = 120.0
    # consecutive missed probes before a READY replica is replaced
    # (≈ the reference's health_check_failure_threshold): one missed
    # 5s probe is routine for a replica busy jit-compiling a new batch
    # shape — killing it then turns every cold shape into an outage
    HEALTH_FAIL_THRESHOLD = 3

    def __init__(self, handle):
        self.handle = handle
        self.created_at = time.time()
        self.health_failures = 0
        self.ready = False


class _DeploymentState:
    def __init__(self, app_name: str, spec: Dict[str, Any]):
        self.app_name = app_name
        self.spec = spec
        self.replicas: List[_ReplicaHolder] = []
        self.version = 0
        self.target = spec["num_replicas"]
        self.status = "UPDATING"
        self.deleted = False
        # prefix-affinity digests (ISSUE 18): replica key -> the digest
        # its stats last reported; version bumps wake listen_for_digests
        self.digests: Dict[str, Dict[str, Any]] = {}
        self.digest_version = 0
        # serializes scale operations: delete (scale→0) racing the
        # reconcile loop (scale→target) would otherwise livelock,
        # alternately killing and recreating the same replica
        self.lock = asyncio.Lock()

    @property
    def name(self) -> str:
        return self.spec["name"]


class ServeController:
    """Async actor; all methods run on one asyncio loop (max_concurrency
    set high by the deployer) so state mutations are single-threaded."""

    def __init__(self):
        self._apps: Dict[str, Dict[str, _DeploymentState]] = {}
        self._routes: Dict[str, str] = {}  # route_prefix -> "app/ingress"
        self._route_asgi: Dict[str, bool] = {}  # "app/ingress" -> is ASGI
        self._shutdown = False
        self._loop_task = None
        # long-poll support (≈ python/ray/serve/_private/long_poll.py):
        # routers hold a listen_for_change call open; any replica-set
        # version bump wakes them
        self._change_event = asyncio.Event()
        # separate event for digest pushes: digests churn far faster than
        # replica sets and must not wake every replica-set listener
        self._digest_event = asyncio.Event()

    def _notify_change(self) -> None:
        self._change_event.set()
        self._change_event = asyncio.Event()

    def _notify_digest(self) -> None:
        self._digest_event.set()
        self._digest_event = asyncio.Event()

    async def _ensure_loop(self):
        if self._loop_task is None:
            self._loop_task = asyncio.ensure_future(self._reconcile_loop())

    # ------------------------------------------------------------ deploy

    async def deploy_application(self, app_name: str,
                                 deployment_specs: List[Dict[str, Any]],
                                 route_prefix: Optional[str],
                                 ingress_name: str) -> None:
        await self._ensure_loop()
        app = self._apps.setdefault(app_name, {})
        new_names = {s["name"] for s in deployment_specs}
        # remove deployments dropped from the app
        for name in list(app):
            if name not in new_names:
                app[name].deleted = True
                await self._scale_to(app[name], 0)
                del app[name]
        for spec in deployment_specs:
            if name_state := app.get(spec["name"]):
                name_state.spec = spec
                name_state.target = spec["num_replicas"]
                name_state.version += 1
                self._notify_change()
            else:
                app[spec["name"]] = _DeploymentState(app_name, spec)
        if route_prefix:
            self._routes[route_prefix] = f"{app_name}/{ingress_name}"
            # ASGI-ness is a static class property (serve.ingress marker):
            # publish it with the route so the proxy never has to probe
            # user code to classify a deployment
            for spec in deployment_specs:
                if spec["name"] == ingress_name:
                    try:
                        cls = spec["callable_factory"]()
                        self._route_asgi[f"{app_name}/{ingress_name}"] = (
                            getattr(cls, "__serve_is_asgi__", False) is True)
                    except Exception:
                        self._route_asgi[
                            f"{app_name}/{ingress_name}"] = False
        await self._reconcile_once()

    async def delete_application(self, app_name: str) -> None:
        app = self._apps.pop(app_name, None)
        if app:
            for st in app.values():
                st.deleted = True
                await self._scale_to(st, 0)
        self._routes = {r: t for r, t in self._routes.items()
                        if not t.startswith(app_name + "/")}
        self._route_asgi = {t: v for t, v in self._route_asgi.items()
                            if not t.startswith(app_name + "/")}

    # --------------------------------------------------------- reconcile

    async def _reconcile_loop(self):
        while not self._shutdown:
            try:
                await self._reconcile_once()
                await self._autoscale()
                await self._collect_digests()
            except Exception:
                logger.exception("reconcile error")
            await asyncio.sleep(0.5)

    async def _collect_digests(self):
        """Pull each ready replica's prefix digest through its stats —
        the controller POLLS, replicas never push (they make zero
        control-plane RPCs in steady state); routers long-poll
        ``listen_for_digests`` and only wake on real digest churn."""
        for app in self._apps.values():
            for st in app.values():
                fresh: Dict[str, Dict[str, Any]] = {}
                for holder in st.replicas:
                    if not holder.ready:
                        continue
                    try:
                        s = await asyncio.wait_for(
                            holder.handle.stats.remote(), timeout=5)
                    except Exception:
                        continue
                    d = s.get("prefix_digest") or {}
                    if d:
                        fresh[holder.handle._actor_id.hex()] = d
                sig_old = {k: v.get("version")
                           for k, v in st.digests.items()}
                sig_new = {k: v.get("version") for k, v in fresh.items()}
                if sig_new != sig_old:
                    st.digests = fresh
                    st.digest_version += 1
                    self._notify_digest()

    async def _reconcile_once(self):
        for app in list(self._apps.values()):
            for st in list(app.values()):
                if st.deleted:
                    continue
                await self._health_sweep(st)
                await self._scale_to(st, st.target)
                ready = sum(1 for h in st.replicas if h.ready)
                st.status = "RUNNING" if ready == st.target else "UPDATING"

    @staticmethod
    def _init_expired(holder: _ReplicaHolder) -> bool:
        return time.time() - holder.created_at > holder.INIT_TIMEOUT_S

    async def _health_sweep(self, st: _DeploymentState):
        # Probe a snapshot, then REMOVE the dead under the lock. Never
        # assign the snapshot back: a concurrent scale-down could have
        # popped a replica mid-probe, and re-assigning would resurrect it.
        snapshot = list(st.replicas)
        dead = []
        for holder in snapshot:
            try:
                ok = await asyncio.wait_for(
                    holder.handle.check_health.remote(), timeout=5)
                if ok:
                    holder.health_failures = 0
                    if not holder.ready:
                        holder.ready = True
                        st.version += 1  # routers learn of the new replica
                        self._notify_change()
                elif holder.ready or self._init_expired(holder):
                    # the replica RESPONDED unhealthy: no benefit of the
                    # doubt — it told us itself
                    logger.warning(
                        "replica of %s reported unhealthy; replacing", st.name)
                    dead.append(holder)
            except Exception as e:
                from ray_tpu._private.exceptions import ActorDiedError

                if holder.ready:
                    holder.health_failures += 1
                    if isinstance(e, ActorDiedError) or \
                            holder.health_failures >= \
                            holder.HEALTH_FAIL_THRESHOLD:
                        # a dead actor is replaced immediately; a slow
                        # probe needs the full consecutive-miss budget
                        logger.warning(
                            "replica of %s failed health check (%d "
                            "consecutive, %s); replacing", st.name,
                            holder.health_failures, type(e).__name__)
                        dead.append(holder)
                elif self._init_expired(holder):
                    logger.warning(
                        "replica of %s never became ready in %.0fs; replacing",
                        st.name, holder.INIT_TIMEOUT_S)
                    dead.append(holder)
                # else: still STARTING — constructor running; leave it be
        if dead:
            async with st.lock:
                before = len(st.replicas)
                st.replicas = [h for h in st.replicas if h not in dead]
                if len(st.replicas) != before:
                    st.version += 1
                    self._notify_change()
            for h in dead:
                try:
                    ray_tpu.kill(h.handle)
                except Exception:
                    pass

    async def _scale_to(self, st: _DeploymentState, n: int):
        from ray_tpu.serve._private.replica import ReplicaActor

        async with st.lock:
            await self._scale_to_locked(st, n, ReplicaActor)

    async def _scale_to_locked(self, st, n, ReplicaActor):
        # Node handoff on deliberate scale-down (opt-in via
        # autoscaling_config["drain_nodes"]). Deletion/teardown (n == 0 on
        # a deleted deployment) never drains: the app is going away, the
        # cluster is not.
        drain = (bool((st.spec.get("autoscaling_config") or {})
                      .get("drain_nodes"))
                 and not st.deleted and n >= 1)
        vacated = set()
        while len(st.replicas) > n:
            holder = st.replicas.pop()
            st.version += 1
            self._notify_change()
            if drain:
                # resolve BEFORE the kill — a dead actor's record may be
                # gone from the controller table by the time we ask
                vacated.add(self._replica_node_hex(holder.handle))
            try:
                await asyncio.wait_for(
                    holder.handle.prepare_for_shutdown.remote(), timeout=15)
            except Exception:
                pass
            try:
                ray_tpu.kill(holder.handle)
            except Exception:
                pass
        if vacated:
            self._drain_vacated_nodes(vacated)
        spec = st.spec
        while len(st.replicas) < n:
            actor_opts = dict(spec.get("ray_actor_options") or {})
            actor_opts.setdefault("num_cpus", 0.1)
            handle = ray_tpu.remote(ReplicaActor).options(
                max_concurrency=spec.get("max_ongoing_requests", 8),
                **actor_opts,
            ).remote(st.app_name, st.name, spec["callable_factory"],
                     spec.get("init_args", ()), spec.get("init_kwargs", {}))
            if spec.get("user_config") is not None:
                await handle.reconfigure.remote(spec["user_config"])
            st.replicas.append(_ReplicaHolder(handle))
            st.version += 1
            self._notify_change()

    # ----------------------------------------------------- node drain

    @staticmethod
    def _replica_node_hex(handle) -> str:
        """Which node hosts this replica, per the cluster controller's
        actor table ("" if unknown)."""
        from ray_tpu._private import api

        core = api._core
        if core is None:
            return ""
        for _ in range(3):  # actor_get is read-only; retries are free
            try:
                rec = core._run(core.clients.get(core.controller_addr).call(
                    "actor_get",
                    {"actor_id_hex": handle._actor_id.hex()}))
                return (rec or {}).get("node_id_hex") or ""
            except Exception:
                continue
        return ""

    def _drain_vacated_nodes(self, candidates) -> None:
        """Retire nodes vacated by a deliberate scale-down NOW, via the
        cluster controller's node_drain RPC, so their channels, pins and
        leases hand off immediately instead of waiting out the crash
        debounce (the drain reason skips recovery_grace_s on peers).
        Opt-in per deployment (autoscaling_config["drain_nodes"]) because
        a drain takes the whole node — only safe when the autoscaled
        replica pool has its nodes to itself. A node still hosting any
        replica of any app, and the controller's own node, are never
        drained."""
        from ray_tpu._private import api

        core = api._core
        if core is None:
            return
        still_used = set()
        for app in self._apps.values():
            for st in app.values():
                for holder in st.replicas:
                    still_used.add(self._replica_node_hex(holder.handle))
        for hexid in sorted(candidates):
            if not hexid or hexid == core.node_id_hex or hexid in still_used:
                continue
            logger.info("draining vacated node %s after scale-down",
                        hexid[:12])
            for attempt in range(3):  # node_drain is idempotent
                try:
                    core._run(core.clients.get(core.controller_addr).call(
                        "node_drain", {"node_id_hex": hexid}))
                    break
                except Exception:
                    if attempt == 2:
                        logger.exception("node_drain failed for %s",
                                         hexid[:12])

    async def _autoscale(self):
        for app in self._apps.values():
            for st in app.values():
                cfg = st.spec.get("autoscaling_config")
                if not cfg:
                    continue
                stats = []
                for holder in st.replicas:
                    if not holder.ready:
                        continue
                    try:
                        stats.append(await asyncio.wait_for(
                            holder.handle.stats.remote(), timeout=5))
                    except Exception:
                        pass
                if not stats:
                    continue
                total_ongoing = sum(s["ongoing"] for s in stats)
                # queued-but-unscheduled work (the continuous batcher's
                # ray_tpu_serve_queue_depth signal, relayed through
                # replica stats) counts toward load: a replica with all
                # slots busy and a deep backlog reports few "ongoing"
                # requests exactly when more replicas are needed most.
                # max(), not +: a queued NON-streaming request is also
                # held open in "ongoing" for its whole await, so summing
                # would double-count the backlog
                total_queued = int(sum(s.get("queue_depth", 0)
                                       for s in stats))
                load = max(total_ongoing, total_queued)
                target_per = cfg.get("target_ongoing_requests", 2)
                desired = max(
                    cfg.get("min_replicas", 1),
                    min(cfg.get("max_replicas", 1),
                        -(-load // target_per) or
                        cfg.get("min_replicas", 1)))
                if desired != st.target:
                    logger.info(
                        "autoscale %s: %d -> %d (ongoing=%d queued=%d)",
                        st.name, st.target, desired, total_ongoing,
                        total_queued)
                    st.target = desired

    # ------------------------------------------------------------- query

    async def get_replicas(self, app_name: str, deployment_name: str):
        st = self._apps.get(app_name, {}).get(deployment_name)
        if st is None:
            return {"version": -1, "replicas": []}
        # routers only see READY replicas (reference: RUNNING state), so a
        # still-initializing model replica never receives traffic
        return {"version": st.version,
                "replicas": [h.handle for h in st.replicas if h.ready],
                "max_ongoing": st.spec.get("max_ongoing_requests", 8)}

    async def listen_for_change(self, app_name: str, deployment_name: str,
                                known_version: int,
                                timeout_s: float = 30.0):
        """Long-poll: returns the replica set as soon as its version differs
        from `known_version`, or the current (unchanged) state after
        timeout_s so the caller can re-arm. Replaces router interval
        polling (≈ LongPollHost.listen_for_change, long_poll.py)."""
        deadline = asyncio.get_running_loop().time() + timeout_s
        while True:
            st = self._apps.get(app_name, {}).get(deployment_name)
            version = st.version if st is not None else -1
            if version != known_version:
                return await self.get_replicas(app_name, deployment_name)
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                return await self.get_replicas(app_name, deployment_name)
            ev = self._change_event
            try:
                await asyncio.wait_for(ev.wait(), timeout=remaining)
            except asyncio.TimeoutError:
                pass

    async def get_digests(self, app_name: str, deployment_name: str):
        st = self._apps.get(app_name, {}).get(deployment_name)
        if st is None:
            return {"version": -1, "digests": {}}
        return {"version": st.digest_version, "digests": dict(st.digests)}

    async def listen_for_digests(self, app_name: str, deployment_name: str,
                                 known_version: int,
                                 timeout_s: float = 30.0):
        """Long-poll for prefix-affinity digests, mirroring
        ``listen_for_change``: returns as soon as the digest version moves
        past ``known_version`` (or unchanged state after ``timeout_s``)."""
        deadline = asyncio.get_running_loop().time() + timeout_s
        while True:
            st = self._apps.get(app_name, {}).get(deployment_name)
            version = st.digest_version if st is not None else -1
            if version != known_version:
                return await self.get_digests(app_name, deployment_name)
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                return await self.get_digests(app_name, deployment_name)
            ev = self._digest_event
            try:
                await asyncio.wait_for(ev.wait(), timeout=remaining)
            except asyncio.TimeoutError:
                pass

    async def get_routes(self) -> Dict[str, str]:
        return dict(self._routes)

    async def get_route_asgi(self) -> Dict[str, bool]:
        """Which route targets are ASGI ingresses (serve.ingress)."""
        return dict(self._route_asgi)

    async def status(self) -> Dict[str, Any]:
        out = {}
        for app_name, app in self._apps.items():
            out[app_name] = {
                name: {"status": st.status, "replicas": len(st.replicas),
                       "target": st.target, "version": st.version}
                for name, st in app.items()
            }
        return out

    async def graceful_shutdown(self) -> None:
        self._shutdown = True
        for app_name in list(self._apps):
            await self.delete_application(app_name)
