"""Request router with power-of-two-choices replica scheduling.

Analog of `ray.serve._private.router.Router.assign_request`
(`python/ray/serve/_private/router.py:518`) +
`PowerOfTwoChoicesReplicaScheduler`
(`_private/replica_scheduler/pow_2_scheduler.py:49`): sample two replicas,
send to the one with the lower locally-tracked in-flight count.

The replica set is pushed, not polled: a background thread holds a
long-poll (`controller.listen_for_change`) open so config changes land
the moment the controller bumps the version — there is no interval
re-listing and no sleep loop in the request hot path
(≈ `python/ray/serve/_private/long_poll.py` LongPollClient).

PREFIX AFFINITY (ISSUE 18): a second long-poll
(`controller.listen_for_digests`) mirrors every replica's radix-cache
chain-hash digest into an `AffinityIndex`; the pick path hashes the
incoming prompt's page-aligned prefix and steers to the replica holding
the deepest match — unless that replica is fail-marked or its in-flight
count exceeds the least-loaded replica's by more than the skew bound, in
which case the pick falls back to pow-2 and the chosen replica gets a
``_fleet_hint`` naming the holder so it can PULL the pages itself.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve._private.affinity import (AffinityIndex, m_affinity_hits,
                                             m_affinity_misses)


class _WatchedStream(ray_tpu.ObjectRefGenerator):
    """ObjectRefGenerator that reports its terminal state (clean
    exhaustion vs task error) back to the router's per-replica failure
    accounting — a replica that only serves streams must still be
    observed when it starts failing (advisor r4). Subclasses rather than
    wraps so handle-side isinstance(ObjectRefGenerator) checks hold."""

    def __init__(self, inner: ray_tpu.ObjectRefGenerator, router: "Router",
                 replica_key: str, mux_id: str = "",
                 inflight_idx: Optional[int] = None):
        super().__init__(inner._task_id, inner._owner_addr)
        # take over stream ownership: the inner generator is dropped
        # right after this call and its __del__ must not release the
        # still-live stream out from under us
        inner._released = True
        self._router = router
        self._replica_key = replica_key
        self._mux_id = mux_id
        # the stream HOLDS its pick's in-flight count until it settles
        # (exhaustion, task error, or consumer abandonment via GC) — a
        # counter released at submit time would make every streaming
        # request invisible to the pow-2 draw AND to the affinity skew
        # bound, letting steering pile streams onto one replica unbounded
        self._inflight_idx = inflight_idx
        self._settled = False

    def _settle(self, ok: Optional[bool] = None) -> None:
        """Release the in-flight count exactly once; optionally feed the
        terminal state into failure accounting."""
        if self._settled:
            return
        self._settled = True
        r = self._router
        idx = self._inflight_idx
        if idx is not None:
            with r._lock:
                if idx in r._inflight and r._inflight[idx] > 0:
                    r._inflight[idx] -= 1
        if ok is not None:
            r._note_result(self._replica_key, ok=ok, mux_id=self._mux_id)

    def _next(self, timeout=None):
        import asyncio
        import concurrent.futures

        try:
            return super()._next(timeout)
        except StopIteration:
            self._settle(ok=True)
            raise
        except (TimeoutError, GeneratorExit, asyncio.CancelledError,
                concurrent.futures.CancelledError):
            # NOT replica failures: a timeout is the CONSUMER's deadline
            # on a slow-but-healthy stream (GetTimeoutError subclasses
            # TimeoutError), GeneratorExit/Cancelled are consumer-side
            # aborts. Marking these would penalize a replica for 10s in
            # the pow-2 draw for merely streaming slowly.
            raise
        except BaseException:
            self._settle(ok=False)
            raise

    next = _next  # re-bind: the base class aliases its own _next

    def __del__(self):
        # consumer dropped the stream mid-iteration: release the count
        # (no terminal verdict — abandonment says nothing about the
        # replica), then let the base class release the stream itself
        try:
            self._settle()
        except Exception:
            pass
        try:
            super().__del__()
        except Exception:
            pass


class Router:
    LONG_POLL_TIMEOUT_S = 30.0

    def __init__(self, controller, app_name: str, deployment_name: str):
        self._controller = controller
        self._app = app_name
        self._deployment = deployment_name
        self._replicas: List[Any] = []
        self._version = -2
        self._inflight: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._update_event = threading.Event()
        self._stopped = False
        self._poll_thread: Optional[threading.Thread] = None
        # multiplexing: model_id -> STABLE replica keys (actor ids, not
        # list indices — a long-poll update reorders/replaces the replica
        # list and index-keyed marks would silently point at different
        # replicas, routing to cold ones until the next mux poll) holding
        # it; refreshed by a background poll only while multiplexed
        # requests flow. Keys translate to indices at pick time.
        self._mux_locations: Dict[str, set] = {}
        self._key_to_idx: Dict[str, int] = {}
        self._mux_thread: Optional[threading.Thread] = None
        # optimistic (model, key) marks with timestamps: kept through
        # refreshes while the model may still be loading on that replica
        self._mux_marks: Dict[tuple, float] = {}
        self._mux_last_request = 0.0
        # replica key -> time of its last observed request failure; fed
        # by unary completions AND stream terminal states (advisor r4:
        # a replica that only serves streams must still be observable),
        # read by _pick to deprioritize recently-failing replicas
        self._fail_marks: Dict[str, float] = {}
        # prefix affinity (ISSUE 18): replica digests mirrored by a
        # second long-poll; steering happens inside _pick
        from ray_tpu._private.config import global_config

        conf = global_config()
        self._affinity_on = bool(conf.serve_affinity)
        self._affinity_skew = int(conf.serve_affinity_skew)
        self._affinity = AffinityIndex()
        self._digest_thread: Optional[threading.Thread] = None

    FAIL_PENALTY_S = 10.0  # how long a failure skews the pow-2 draw

    def _note_result(self, key: str, ok: bool, mux_id: str = "") -> None:
        with self._lock:
            if ok:
                self._fail_marks.pop(key, None)
            else:
                self._fail_marks[key] = time.monotonic()
                if mux_id:
                    # the optimistic "this replica will hold the model
                    # after this request" insert (assign_request) is now
                    # known false — the request died, likely before the
                    # model loaded. Left in place it steers sibling
                    # requests at a cold (or dead) replica for up to
                    # MUX_MARK_TTL_S; drop it and let the next refresh
                    # poll re-observe reality.
                    self._mux_marks.pop((mux_id, key), None)
                    locs = self._mux_locations.get(mux_id)
                    if locs is not None:
                        locs.discard(key)
                        if not locs:
                            self._mux_locations.pop(mux_id, None)

    @staticmethod
    def _replica_key(rep) -> str:
        aid = getattr(rep, "_actor_id", None)
        return aid.hex() if aid is not None else repr(rep)

    def _ensure_polling(self) -> None:
        if self._poll_thread is None:
            with self._lock:
                if self._poll_thread is None:
                    t = threading.Thread(
                        target=self._poll_loop,
                        name=f"serve-longpoll-{self._deployment}",
                        daemon=True,
                    )
                    self._poll_thread = t
                    t.start()

    def _poll_loop(self) -> None:
        """Keep one listen_for_change call in flight; apply each push.
        If the controller stays unreachable (serve.shutdown), the thread
        retires itself; the next assign_request restarts polling."""
        failures = 0
        while not self._stopped:
            try:
                info = ray_tpu.get(
                    self._controller.listen_for_change.remote(
                        self._app, self._deployment, self._version,
                        self.LONG_POLL_TIMEOUT_S),
                    timeout=self.LONG_POLL_TIMEOUT_S + 30,
                )
            except Exception:
                if self._stopped:
                    return
                failures += 1
                if failures >= 10:
                    with self._lock:
                        self._replicas = []
                        self._version = -2
                        self._poll_thread = None
                    return
                time.sleep(min(0.2 * failures, 2.0))
                continue
            failures = 0
            if info["version"] != self._version:
                with self._lock:
                    self._replicas = info["replicas"]
                    self._version = info["version"]
                    self._inflight = {
                        i: 0 for i in range(len(self._replicas))}
                    self._key_to_idx = {
                        self._replica_key(r): i
                        for i, r in enumerate(self._replicas)}
                self._update_event.set()

    def _ensure_digest_polling(self) -> None:
        if self._digest_thread is None:
            with self._lock:
                if self._digest_thread is None:
                    t = threading.Thread(
                        target=self._digest_poll_loop,
                        name=f"serve-digests-{self._deployment}",
                        daemon=True,
                    )
                    self._digest_thread = t
                    t.start()

    def _digest_poll_loop(self) -> None:
        """Mirror replica prefix digests via a long-poll on the
        controller (which in turn reads them off its EXISTING replica
        stats poll — no new steady-state RPC originates at any replica).
        Retires itself if the controller stays unreachable; the next
        affinity-eligible request restarts it."""
        failures = 0
        while not self._stopped:
            with self._lock:
                known = self._affinity.version
            try:
                info = ray_tpu.get(
                    self._controller.listen_for_digests.remote(
                        self._app, self._deployment, known,
                        self.LONG_POLL_TIMEOUT_S),
                    timeout=self.LONG_POLL_TIMEOUT_S + 30,
                )
            except Exception:
                if self._stopped:
                    return
                failures += 1
                if failures >= 10:
                    with self._lock:
                        self._digest_thread = None
                    return
                time.sleep(min(0.2 * failures, 2.0))
                continue
            failures = 0
            with self._lock:
                self._affinity.update(info)

    def _affinity_chain(self, args) -> Optional[list]:
        """Chain-hash the incoming prompt for steering, or None when the
        request is not an LLM payload / no digest data has arrived yet."""
        req = args[0] if args else None
        if isinstance(req, str):
            prompt, ids = req, None
        elif isinstance(req, dict):
            prompt = req.get("prompt") or ""
            ids = req.get("prompt_ids")
        else:
            return None
        if not prompt and not ids:
            return None
        self._ensure_digest_polling()
        with self._lock:
            if not self._affinity.ready():
                return None
            chain = self._affinity.chain_for(prompt, prompt_ids=ids)
        return chain or None

    @staticmethod
    def _attach_hint(args, hint: Dict[str, Any]):
        """Return args with ``_fleet_hint`` injected into a COPY of the
        request payload — the caller's dict must not be mutated."""
        req = args[0]
        req = dict(req) if isinstance(req, dict) else {"prompt": req}
        req["_fleet_hint"] = hint
        return (req,) + tuple(args[1:])

    def _pick(self, multiplexed_model_id: str = "",
              chain: Optional[list] = None):
        """Pow-2 choice under the lock; None if no replicas known. With a
        model id, restrict the pow-2 draw to replicas already holding that
        model (reference `multiplex.py` routing affinity) when any do.

        With a prefix ``chain`` (ISSUE 18), steer to the replica whose
        radix cache matches the deepest page-aligned prefix — unless it is
        fail-marked or its in-flight count exceeds the least-loaded
        replica's by more than the skew bound, in which case fall back to
        pow-2 and return a ``_fleet_hint`` so the chosen replica can pull
        the pages from the holder. Returns (idx, replica, hint|None)."""
        with self._lock:
            n = len(self._replicas)
            if not n:
                return None
            candidates = list(range(n))
            if multiplexed_model_id:
                hot = self._mux_locations.get(multiplexed_model_id)
                if hot:
                    hot_idx = [self._key_to_idx[k] for k in hot
                               if k in self._key_to_idx]
                    if hot_idx:
                        candidates = hot_idx
            hint = None
            steered = None
            holder_idx = None
            if chain:
                keys = [self._replica_key(r) for r in self._replicas]
                holder_key, depth = self._affinity.steer(chain, keys)
                if holder_key is not None and holder_key in self._key_to_idx:
                    holder_idx = self._key_to_idx[holder_key]
                    now = time.monotonic()
                    failing = (now - self._fail_marks.get(holder_key, 0.0)
                               < self.FAIL_PENALTY_S)
                    min_load = min(self._inflight.get(i, 0)
                                   for i in candidates)
                    skewed = (self._inflight.get(holder_idx, 0) - min_load
                              > self._affinity_skew)
                    if (holder_idx in candidates and not failing
                            and not skewed):
                        steered = holder_idx
                        m_affinity_hits.inc()
                    else:
                        # holder known but unusable: pow-2 below, and tell
                        # the chosen replica where to PULL the prefix from
                        hint = {
                            "handle": self._replicas[holder_idx],
                            "tokens": depth * self._affinity.page_tokens,
                        }
                        m_affinity_misses.inc()
                else:
                    m_affinity_misses.inc()
            if steered is not None:
                idx = steered
            elif len(candidates) == 1:
                idx = candidates[0]
            else:
                now = time.monotonic()

                def load(i):
                    # a recent failure outweighs any plausible in-flight
                    # difference without permanently blacklisting
                    key = self._replica_key(self._replicas[i])
                    mark = self._fail_marks.get(key, 0.0)
                    penalty = 1000 if now - mark < self.FAIL_PENALTY_S else 0
                    return self._inflight.get(i, 0) + penalty

                a, b = random.sample(candidates, 2)
                idx = a if load(a) <= load(b) else b
            if hint is not None and (idx == holder_idx
                                     or not hint["tokens"]):
                hint = None  # landed on the holder anyway / nothing to pull
            self._inflight[idx] = self._inflight.get(idx, 0) + 1
            return idx, self._replicas[idx], hint

    def assign_request(self, method_name: str, args, kwargs):
        ref, _replica = self.assign_request_with_replica(
            method_name, args, kwargs)
        return ref

    def assign_request_with_replica(self, method_name: str, args, kwargs,
                                    multiplexed_model_id: str = "",
                                    streaming: bool = False):
        """Returns (result_ref, replica_handle) — or, with streaming=True,
        (ObjectRefGenerator, replica_handle): the request rides the native
        generator transport (replica.handle_request_streaming) and chunks
        arrive as owner-owned ObjectRefs as they are produced. The replica
        handle lets callers continue a chunk-pull streaming response on
        the same replica (legacy path)."""
        self._ensure_polling()
        if multiplexed_model_id:
            self._ensure_mux_refresh()
        chain = None
        if self._affinity_on and not multiplexed_model_id:
            chain = self._affinity_chain(args)
        deadline = time.monotonic() + 30
        while True:
            # clear BEFORE picking: a push landing between a failed pick
            # and clear() would otherwise be erased and stall us a full
            # wait interval
            self._update_event.clear()
            picked = self._pick(multiplexed_model_id, chain)
            if picked is not None:
                idx, replica, hint = picked
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise RuntimeError(
                    f"no replicas for {self._app}/{self._deployment}")
            # wait for the long-poll push, not an interval
            self._update_event.wait(timeout=min(remaining, 5.0))
        if hint is not None:
            args = self._attach_hint(args, hint)
        if multiplexed_model_id:
            # optimistic: the chosen replica will hold the model after this
            # request, so siblings route there before the next poll lands
            key = self._replica_key(replica)
            with self._lock:
                self._mux_locations.setdefault(
                    multiplexed_model_id, set()).add(key)
                self._mux_marks[(multiplexed_model_id, key)] = (
                    time.monotonic())
                self._mux_last_request = time.monotonic()
        if streaming:
            gen = replica.handle_request_streaming.options(
                num_returns="streaming").remote(method_name, args, kwargs)
            # in-flight accounting: the watched wrapper holds the count
            # for the STREAM's lifetime and releases it at exhaustion,
            # task error, or consumer GC — releasing at submit would hide
            # every streaming request from the pow-2 draw and from the
            # affinity skew bound (steering would pile streams onto the
            # digest holder unbounded). Terminal state still feeds
            # failure accounting via the wrapper (advisor r4).
            return (_WatchedStream(gen, self, self._replica_key(replica),
                                   mux_id=multiplexed_model_id,
                                   inflight_idx=idx),
                    replica)
        ref = replica.handle_request.remote(method_name, args, kwargs)
        self._watch_completion(ref, idx, self._replica_key(replica),
                               mux_id=multiplexed_model_id)
        return ref, replica

    def _ensure_mux_refresh(self) -> None:
        self._mux_last_request = time.monotonic()
        if self._mux_thread is None:
            with self._lock:
                if self._mux_thread is None:
                    t = threading.Thread(
                        target=self._mux_refresh_loop,
                        name=f"serve-mux-{self._deployment}", daemon=True)
                    self._mux_thread = t
                    t.start()

    MUX_MARK_TTL_S = 30.0     # optimistic marks survive refreshes this long
    MUX_IDLE_EXIT_S = 60.0    # refresh thread retires when mux traffic stops

    def _mux_refresh_loop(self) -> None:
        """Poll replicas' loaded-model sets so affinity reflects real LRU
        state (evictions included). Recent optimistic marks and entries of
        unreachable replicas are merged in, not wiped — a model mid-load
        (or one slow poll) must not bounce the next request to a cold
        replica. The thread retires itself once mux traffic stops."""
        while not self._stopped:
            time.sleep(1.0)
            now = time.monotonic()
            if now - self._mux_last_request > self.MUX_IDLE_EXIT_S:
                with self._lock:
                    self._mux_thread = None
                return
            with self._lock:
                replicas = list(enumerate(self._replicas))
            if not replicas:
                continue
            fresh: Dict[str, set] = {}
            failed: set = set()
            for _idx, rep in replicas:
                key = self._replica_key(rep)
                try:
                    info = ray_tpu.get(rep.multiplex_info.remote(),
                                       timeout=5)
                except Exception:
                    failed.add(key)
                    continue
                for mid in info.get("model_ids", ()):
                    fresh.setdefault(mid, set()).add(key)
            with self._lock:
                for (mid, key), ts in list(self._mux_marks.items()):
                    if now - ts > self.MUX_MARK_TTL_S:
                        del self._mux_marks[(mid, key)]
                    else:
                        fresh.setdefault(mid, set()).add(key)
                for mid, idxs in self._mux_locations.items():
                    keep = idxs & failed
                    if keep:
                        fresh.setdefault(mid, set()).update(keep)
                self._mux_locations = fresh

    def _watch_completion(self, ref, idx: int, key: str, mux_id: str = ""):
        def done(f):
            with self._lock:
                if idx in self._inflight and self._inflight[idx] > 0:
                    self._inflight[idx] -= 1
            try:
                self._note_result(key, ok=f.exception() is None,
                                  mux_id=mux_id)
            except Exception:
                pass

        try:
            ref.future().add_done_callback(done)
        except Exception:
            with self._lock:
                if idx in self._inflight and self._inflight[idx] > 0:
                    self._inflight[idx] -= 1

    def stop(self) -> None:
        self._stopped = True
