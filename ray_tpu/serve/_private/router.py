"""Request router with power-of-two-choices replica scheduling.

Analog of `ray.serve._private.router.Router.assign_request`
(`python/ray/serve/_private/router.py:518`) +
`PowerOfTwoChoicesReplicaScheduler`
(`_private/replica_scheduler/pow_2_scheduler.py:49`): sample two replicas,
send to the one with the lower locally-tracked in-flight count.

The replica set is pushed, not polled: a background thread holds a
long-poll (`controller.listen_for_change`) open so config changes land
the moment the controller bumps the version — there is no interval
re-listing and no sleep loop in the request hot path
(≈ `python/ray/serve/_private/long_poll.py` LongPollClient).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu


class _WatchedStream(ray_tpu.ObjectRefGenerator):
    """ObjectRefGenerator that reports its terminal state (clean
    exhaustion vs task error) back to the router's per-replica failure
    accounting — a replica that only serves streams must still be
    observed when it starts failing (advisor r4). Subclasses rather than
    wraps so handle-side isinstance(ObjectRefGenerator) checks hold."""

    def __init__(self, inner: ray_tpu.ObjectRefGenerator, router: "Router",
                 replica_key: str):
        super().__init__(inner._task_id, inner._owner_addr)
        # take over stream ownership: the inner generator is dropped
        # right after this call and its __del__ must not release the
        # still-live stream out from under us
        inner._released = True
        self._router = router
        self._replica_key = replica_key

    def _next(self, timeout=None):
        import asyncio
        import concurrent.futures

        try:
            return super()._next(timeout)
        except StopIteration:
            self._router._note_result(self._replica_key, ok=True)
            raise
        except (TimeoutError, GeneratorExit, asyncio.CancelledError,
                concurrent.futures.CancelledError):
            # NOT replica failures: a timeout is the CONSUMER's deadline
            # on a slow-but-healthy stream (GetTimeoutError subclasses
            # TimeoutError), GeneratorExit/Cancelled are consumer-side
            # aborts. Marking these would penalize a replica for 10s in
            # the pow-2 draw for merely streaming slowly.
            raise
        except BaseException:
            self._router._note_result(self._replica_key, ok=False)
            raise

    next = _next  # re-bind: the base class aliases its own _next


class Router:
    LONG_POLL_TIMEOUT_S = 30.0

    def __init__(self, controller, app_name: str, deployment_name: str):
        self._controller = controller
        self._app = app_name
        self._deployment = deployment_name
        self._replicas: List[Any] = []
        self._version = -2
        self._inflight: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._update_event = threading.Event()
        self._stopped = False
        self._poll_thread: Optional[threading.Thread] = None
        # multiplexing: model_id -> STABLE replica keys (actor ids, not
        # list indices — a long-poll update reorders/replaces the replica
        # list and index-keyed marks would silently point at different
        # replicas, routing to cold ones until the next mux poll) holding
        # it; refreshed by a background poll only while multiplexed
        # requests flow. Keys translate to indices at pick time.
        self._mux_locations: Dict[str, set] = {}
        self._key_to_idx: Dict[str, int] = {}
        self._mux_thread: Optional[threading.Thread] = None
        # optimistic (model, key) marks with timestamps: kept through
        # refreshes while the model may still be loading on that replica
        self._mux_marks: Dict[tuple, float] = {}
        self._mux_last_request = 0.0
        # replica key -> time of its last observed request failure; fed
        # by unary completions AND stream terminal states (advisor r4:
        # a replica that only serves streams must still be observable),
        # read by _pick to deprioritize recently-failing replicas
        self._fail_marks: Dict[str, float] = {}

    FAIL_PENALTY_S = 10.0  # how long a failure skews the pow-2 draw

    def _note_result(self, key: str, ok: bool) -> None:
        with self._lock:
            if ok:
                self._fail_marks.pop(key, None)
            else:
                self._fail_marks[key] = time.monotonic()

    @staticmethod
    def _replica_key(rep) -> str:
        aid = getattr(rep, "_actor_id", None)
        return aid.hex() if aid is not None else repr(rep)

    def _ensure_polling(self) -> None:
        if self._poll_thread is None:
            with self._lock:
                if self._poll_thread is None:
                    t = threading.Thread(
                        target=self._poll_loop,
                        name=f"serve-longpoll-{self._deployment}",
                        daemon=True,
                    )
                    self._poll_thread = t
                    t.start()

    def _poll_loop(self) -> None:
        """Keep one listen_for_change call in flight; apply each push.
        If the controller stays unreachable (serve.shutdown), the thread
        retires itself; the next assign_request restarts polling."""
        failures = 0
        while not self._stopped:
            try:
                info = ray_tpu.get(
                    self._controller.listen_for_change.remote(
                        self._app, self._deployment, self._version,
                        self.LONG_POLL_TIMEOUT_S),
                    timeout=self.LONG_POLL_TIMEOUT_S + 30,
                )
            except Exception:
                if self._stopped:
                    return
                failures += 1
                if failures >= 10:
                    with self._lock:
                        self._replicas = []
                        self._version = -2
                        self._poll_thread = None
                    return
                time.sleep(min(0.2 * failures, 2.0))
                continue
            failures = 0
            if info["version"] != self._version:
                with self._lock:
                    self._replicas = info["replicas"]
                    self._version = info["version"]
                    self._inflight = {
                        i: 0 for i in range(len(self._replicas))}
                    self._key_to_idx = {
                        self._replica_key(r): i
                        for i, r in enumerate(self._replicas)}
                self._update_event.set()

    def _pick(self, multiplexed_model_id: str = ""):
        """Pow-2 choice under the lock; None if no replicas known. With a
        model id, restrict the pow-2 draw to replicas already holding that
        model (reference `multiplex.py` routing affinity) when any do."""
        with self._lock:
            n = len(self._replicas)
            if not n:
                return None
            candidates = list(range(n))
            if multiplexed_model_id:
                hot = self._mux_locations.get(multiplexed_model_id)
                if hot:
                    hot_idx = [self._key_to_idx[k] for k in hot
                               if k in self._key_to_idx]
                    if hot_idx:
                        candidates = hot_idx
            if len(candidates) == 1:
                idx = candidates[0]
            else:
                now = time.monotonic()

                def load(i):
                    # a recent failure outweighs any plausible in-flight
                    # difference without permanently blacklisting
                    key = self._replica_key(self._replicas[i])
                    mark = self._fail_marks.get(key, 0.0)
                    penalty = 1000 if now - mark < self.FAIL_PENALTY_S else 0
                    return self._inflight.get(i, 0) + penalty

                a, b = random.sample(candidates, 2)
                idx = a if load(a) <= load(b) else b
            self._inflight[idx] = self._inflight.get(idx, 0) + 1
            return idx, self._replicas[idx]

    def assign_request(self, method_name: str, args, kwargs):
        ref, _replica = self.assign_request_with_replica(
            method_name, args, kwargs)
        return ref

    def assign_request_with_replica(self, method_name: str, args, kwargs,
                                    multiplexed_model_id: str = "",
                                    streaming: bool = False):
        """Returns (result_ref, replica_handle) — or, with streaming=True,
        (ObjectRefGenerator, replica_handle): the request rides the native
        generator transport (replica.handle_request_streaming) and chunks
        arrive as owner-owned ObjectRefs as they are produced. The replica
        handle lets callers continue a chunk-pull streaming response on
        the same replica (legacy path)."""
        self._ensure_polling()
        if multiplexed_model_id:
            self._ensure_mux_refresh()
        deadline = time.monotonic() + 30
        while True:
            # clear BEFORE picking: a push landing between a failed pick
            # and clear() would otherwise be erased and stall us a full
            # wait interval
            self._update_event.clear()
            picked = self._pick(multiplexed_model_id)
            if picked is not None:
                idx, replica = picked
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise RuntimeError(
                    f"no replicas for {self._app}/{self._deployment}")
            # wait for the long-poll push, not an interval
            self._update_event.wait(timeout=min(remaining, 5.0))
        if multiplexed_model_id:
            # optimistic: the chosen replica will hold the model after this
            # request, so siblings route there before the next poll lands
            key = self._replica_key(replica)
            with self._lock:
                self._mux_locations.setdefault(
                    multiplexed_model_id, set()).add(key)
                self._mux_marks[(multiplexed_model_id, key)] = (
                    time.monotonic())
                self._mux_last_request = time.monotonic()
        if streaming:
            gen = replica.handle_request_streaming.options(
                num_returns="streaming").remote(method_name, args, kwargs)
            # in-flight accounting: count the submit only — stream
            # lifetime is tracked replica-side (_active_streams feeds
            # autoscaling), and a long-lived stream must not permanently
            # skew the pow-2 counter. Terminal state still feeds failure
            # accounting via the watched wrapper (advisor r4).
            with self._lock:
                if idx in self._inflight and self._inflight[idx] > 0:
                    self._inflight[idx] -= 1
            return (_WatchedStream(gen, self, self._replica_key(replica)),
                    replica)
        ref = replica.handle_request.remote(method_name, args, kwargs)
        self._watch_completion(ref, idx, self._replica_key(replica))
        return ref, replica

    def _ensure_mux_refresh(self) -> None:
        self._mux_last_request = time.monotonic()
        if self._mux_thread is None:
            with self._lock:
                if self._mux_thread is None:
                    t = threading.Thread(
                        target=self._mux_refresh_loop,
                        name=f"serve-mux-{self._deployment}", daemon=True)
                    self._mux_thread = t
                    t.start()

    MUX_MARK_TTL_S = 30.0     # optimistic marks survive refreshes this long
    MUX_IDLE_EXIT_S = 60.0    # refresh thread retires when mux traffic stops

    def _mux_refresh_loop(self) -> None:
        """Poll replicas' loaded-model sets so affinity reflects real LRU
        state (evictions included). Recent optimistic marks and entries of
        unreachable replicas are merged in, not wiped — a model mid-load
        (or one slow poll) must not bounce the next request to a cold
        replica. The thread retires itself once mux traffic stops."""
        while not self._stopped:
            time.sleep(1.0)
            now = time.monotonic()
            if now - self._mux_last_request > self.MUX_IDLE_EXIT_S:
                with self._lock:
                    self._mux_thread = None
                return
            with self._lock:
                replicas = list(enumerate(self._replicas))
            if not replicas:
                continue
            fresh: Dict[str, set] = {}
            failed: set = set()
            for _idx, rep in replicas:
                key = self._replica_key(rep)
                try:
                    info = ray_tpu.get(rep.multiplex_info.remote(),
                                       timeout=5)
                except Exception:
                    failed.add(key)
                    continue
                for mid in info.get("model_ids", ()):
                    fresh.setdefault(mid, set()).add(key)
            with self._lock:
                for (mid, key), ts in list(self._mux_marks.items()):
                    if now - ts > self.MUX_MARK_TTL_S:
                        del self._mux_marks[(mid, key)]
                    else:
                        fresh.setdefault(mid, set()).add(key)
                for mid, idxs in self._mux_locations.items():
                    keep = idxs & failed
                    if keep:
                        fresh.setdefault(mid, set()).update(keep)
                self._mux_locations = fresh

    def _watch_completion(self, ref, idx: int, key: str):
        def done(f):
            with self._lock:
                if idx in self._inflight and self._inflight[idx] > 0:
                    self._inflight[idx] -= 1
            try:
                self._note_result(key, ok=f.exception() is None)
            except Exception:
                pass

        try:
            ref.future().add_done_callback(done)
        except Exception:
            with self._lock:
                if idx in self._inflight and self._inflight[idx] > 0:
                    self._inflight[idx] -= 1

    def stop(self) -> None:
        self._stopped = True
