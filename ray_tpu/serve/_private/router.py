"""Request router with power-of-two-choices replica scheduling.

Analog of `ray.serve._private.router.Router.assign_request`
(`python/ray/serve/_private/router.py:518`) +
`PowerOfTwoChoicesReplicaScheduler`
(`_private/replica_scheduler/pow_2_scheduler.py:49`): sample two replicas,
send to the one with the lower locally-tracked in-flight count; refresh
the replica set from the controller when its version bumps.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu


class Router:
    REFRESH_INTERVAL_S = 1.0

    def __init__(self, controller, app_name: str, deployment_name: str):
        self._controller = controller
        self._app = app_name
        self._deployment = deployment_name
        self._replicas: List[Any] = []
        self._version = -2
        self._inflight: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._last_refresh = 0.0

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_refresh < self.REFRESH_INTERVAL_S:
            return
        self._last_refresh = now
        info = ray_tpu.get(
            self._controller.get_replicas.remote(self._app, self._deployment))
        if info["version"] != self._version:
            with self._lock:
                self._replicas = info["replicas"]
                self._version = info["version"]
                self._inflight = {i: 0 for i in range(len(self._replicas))}

    def assign_request(self, method_name: str, args, kwargs):
        deadline = time.monotonic() + 30
        while True:
            self._refresh()
            # select under the same lock acquisition as the length check —
            # a concurrent _refresh can otherwise shrink the list in between.
            with self._lock:
                n = len(self._replicas)
                if n:
                    if n == 1:
                        idx = 0
                    else:
                        a, b = random.sample(range(n), 2)
                        idx = (a if self._inflight.get(a, 0)
                               <= self._inflight.get(b, 0) else b)
                    self._inflight[idx] = self._inflight.get(idx, 0) + 1
                    replica = self._replicas[idx]
                    break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no replicas for {self._app}/{self._deployment}")
            self._refresh(force=True)
            time.sleep(0.05)
        ref = replica.handle_request.remote(method_name, args, kwargs)
        self._watch_completion(ref, idx)
        return ref

    def _watch_completion(self, ref, idx: int):
        def done(_f):
            with self._lock:
                if idx in self._inflight and self._inflight[idx] > 0:
                    self._inflight[idx] -= 1

        try:
            ref.future().add_done_callback(done)
        except Exception:
            with self._lock:
                if idx in self._inflight and self._inflight[idx] > 0:
                    self._inflight[idx] -= 1
