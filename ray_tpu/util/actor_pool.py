"""ActorPool — fan work over a fixed set of actors.

Analog of `ray.util.ActorPool` (`python/ray/util/actor_pool.py`): submit
tasks to whichever pooled actor is free, collect results in submission
order (`map`/`get_next`) or completion order (`map_unordered`/
`get_next_unordered`); actors can be added (`push`) or checked out
(`pop_idle`) while work is in flight.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional

import ray_tpu


class ActorPool:
    def __init__(self, actors: Iterable[Any]):
        self._idle: List[Any] = list(actors)
        # a future's actor is tracked only while in flight (recycled as
        # soon as the task completes); its index mapping lives until the
        # caller consumes the result
        self._inflight_actor = {}
        self._future_to_index = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0

    # ------------------------------------------------------------------ map

    def map(self, fn: Callable[[Any, Any], Any], values: Iterable[Any]
            ) -> Iterator[Any]:
        """Results in submission order. `fn(actor, value)` must return an
        ObjectRef (e.g. `lambda a, v: a.work.remote(v)`)."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]) -> Iterator[Any]:
        """Results in completion order."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # --------------------------------------------------------------- submit

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """Run fn(actor, value) on a free actor (blocks for one to free up
        when the pool is saturated)."""
        if not self._idle:
            # recycle the earliest-completed in-flight task's actor
            ready, _ = ray_tpu.wait(list(self._inflight_actor),
                                    num_returns=1)
            self._return_actor(ready[0])
        actor = self._idle.pop()
        future = fn(actor, value)
        self._inflight_actor[future] = actor
        self._future_to_index[future] = self._next_task_index
        self._index_to_future[self._next_task_index] = future
        self._next_task_index += 1

    def _return_actor(self, future) -> None:
        actor = self._inflight_actor.pop(future, None)
        if actor is not None:
            self._idle.append(actor)

    def has_next(self) -> bool:
        return bool(self._index_to_future)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in SUBMISSION order.

        On timeout raises TimeoutError WITHOUT consuming the task: the
        cursor and mappings are only advanced once the result is ready, so
        the caller can retry and the result is never dropped.
        """
        if not self.has_next():
            raise StopIteration("no more results")
        idx = self._next_return_index
        future = self._index_to_future[idx]
        ready, _ = ray_tpu.wait([future], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError(f"result {idx} not ready within {timeout}s")
        self._index_to_future.pop(idx)
        self._future_to_index.pop(future, None)
        self._next_return_index += 1
        self._return_actor(future)
        return ray_tpu.get(future)

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Next result in COMPLETION order."""
        if not self.has_next():
            raise StopIteration("no more results")
        ready, _ = ray_tpu.wait(list(self._index_to_future.values()),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no result within timeout")
        future = ready[0]
        idx = self._future_to_index.pop(future)
        self._index_to_future.pop(idx)
        self._return_actor(future)
        return ray_tpu.get(future)

    # ------------------------------------------------------------ membership

    def push(self, actor: Any) -> None:
        """Add an idle actor to the pool."""
        self._idle.append(actor)

    def pop_idle(self) -> Optional[Any]:
        """Remove and return an idle actor (None if all are busy)."""
        return self._idle.pop() if self._idle else None

    def has_free(self) -> bool:
        return bool(self._idle)
