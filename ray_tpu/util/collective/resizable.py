"""Elastic world membership for declarative collective groups.

A ``ResizableGroup`` is the driver-side wrapper that turns worker loss
from a terminal event into a *resharding* event (the Podracer
preemption model, arXiv:2104.06272): between operations — never
mid-round, the PR-4 poison invariant stays untouched — the group is
atomically re-declared at the live membership via a fresh rendezvous
generation. The pieces were already in the substrate:

  * ``create_collective_group`` advances a never-deleted generation
    counter (``declgen:{name}``) and folds it into every wire key
    (``{name}@{gen}``) — the monotonic epoch. Straggler frames from the
    old world carry the old generation's keys and can never fold into
    the new world's rounds.
  * A survivor whose member object was poisoned by the departure (or is
    merely stale) recovers through ``BaseGroup._raise_if_stale`` /
    :func:`refresh_membership`: the cached member is destroyed and the
    next collective call lazily re-rendezvouses at the new generation.
    Poison is a *generation-local* verdict, not a process death
    sentence.
  * Joiners receive the current param/optimizer tree leaf-wise over
    ``collective.broadcast`` from a live rank (:func:`sync_tree`) — no
    checkpoint restore anywhere on the rejoin path.

Driver-side state machine: ``ready`` --(death fan-out)-->
``resize_pending`` --(:meth:`ResizableGroup.resize` at the next flush /
step boundary)--> ``ready`` at the new world size. The workloads
(``train.PipelineTrainer``, ``rllib.SebulbaTopology``) own *when* the
boundary is; this module owns the membership bookkeeping and the
rendezvous mechanics.
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, List, Optional, Sequence

import numpy as np

from ray_tpu._private import flight
from ray_tpu.util.collective.collective import (
    _KV_NS,
    _kv,
    _manager,
    broadcast,
    create_collective_group,
    get_rank,
)

_F_RESIZE = flight.intern("elastic.resize")


def _actor_hex(actor_or_hex: Any) -> str:
    if isinstance(actor_or_hex, str):
        return actor_or_hex
    return actor_or_hex._actor_id.hex()


class ResizableGroup:
    """Declarative collective group whose world size can change between
    operations.

    The driver constructs it with the initial rank-ordered actor roster;
    the death fan-out calls :meth:`note_departure` which marks the group
    ``resize_pending`` (members poisoned by the departure stay poisoned
    only within the old generation); at the next operation boundary the
    workload calls :meth:`resize` with the healed roster and every
    survivor re-rendezvouses at the new generation on its next
    collective call — rank assignment is positional in the new roster,
    so gradient MEAN re-scales to the live world by construction.
    """

    def __init__(self, actors: Sequence[Any], *, group_name: str,
                 backend: str = "host"):
        if not actors:
            raise ValueError("ResizableGroup needs at least one actor")
        self.name = group_name
        self._backend = backend
        self._lock = threading.Lock()
        self._actors: List[Any] = list(actors)
        self._departed: set = set()
        self._resize_pending = False
        self._epoch = -1
        self._declare_locked()

    # -- introspection

    @property
    def world_size(self) -> int:
        return len(self._actors)

    @property
    def epoch(self) -> int:
        """The declarative generation the current world rendezvouses
        under — folded into every wire key, monotonic across resizes."""
        return self._epoch

    @property
    def resize_pending(self) -> bool:
        return self._resize_pending

    def actors(self) -> List[Any]:
        return list(self._actors)

    def survivors(self) -> List[Any]:
        return [a for a in self._actors
                if a._actor_id.hex() not in self._departed]

    # -- membership transitions

    def note_departure(self, actor_or_hex: Any) -> bool:
        """A member died (node/actor death fan-out): mark the group
        ``resize_pending``. The wire state of the old generation may be
        poisoned mid-round — that is fine and REQUIRED (the poison
        invariant): survivors recover by joining the next generation,
        never by resuming the torn round. Returns True if the id was a
        live member."""
        hexid = _actor_hex(actor_or_hex)
        with self._lock:
            known = any(a._actor_id.hex() == hexid for a in self._actors)
            if known and hexid not in self._departed:
                self._departed.add(hexid)
                self._resize_pending = True
                from ray_tpu._private.elastic import m_departures

                m_departures.inc(labels={"group": self.name})
                return True
        return False

    def resize(self, actors: Optional[Sequence[Any]] = None) -> int:
        """Atomically re-form the group at the new membership.

        Call this only between operations (flush/step boundary — no
        member may be inside a round). ``actors`` is the new rank-ordered
        roster; None means "the survivors of the noted departures"
        (shrink). Re-declares the group at a fresh generation and
        returns the new epoch; survivors (and joiners) rendezvous lazily
        on their next collective call after dropping stale members via
        :func:`refresh_membership` / ``_raise_if_stale``.
        """
        t0 = flight.now()
        with self._lock:
            roster = (list(actors) if actors is not None
                      else [a for a in self._actors
                            if a._actor_id.hex() not in self._departed])
            if not roster:
                raise RuntimeError(
                    f"resizable group {self.name!r} has no survivors to "
                    f"resize to")
            self._actors = roster
            self._departed.clear()
            self._resize_pending = False
            self._declare_locked()
        from ray_tpu._private.elastic import m_reshards

        m_reshards.inc(labels={"group": self.name})
        flight.span_since(_F_RESIZE, t0)
        return self._epoch

    def _declare_locked(self) -> None:
        n = len(self._actors)
        create_collective_group(
            self._actors, n, list(range(n)), backend=self._backend,
            group_name=self.name)
        meta = _kv().kv_get(f"decl:{self.name}", ns=_KV_NS)
        self._epoch = int(meta["gen"])


# ---------------------------------------------------------- member helpers


def refresh_membership(group_name: str) -> bool:
    """Member-side half of a resize: proactively drop this process's
    cached group member if the driver re-declared the group at a newer
    generation, so the NEXT collective call re-rendezvouses in the new
    world instead of timing out against the old one. This is the
    success-path twin of ``BaseGroup._raise_if_stale`` (which runs only
    after a failure). Returns True if a stale member was dropped.

    A member poisoned by a mid-round departure is covered too:
    destroying it clears the poison along with the stale wire state —
    the poison verdict is generation-local.
    """
    group = _manager.get(group_name)
    if group is None:
        return False
    gen = getattr(group, "_decl_gen", None)
    if gen is None:
        # imperative member: generations don't apply — the caller must
        # destroy/re-init explicitly (the Sebulba bcast-group path)
        return False
    meta = _kv().kv_get(f"decl:{group_name}", ns=_KV_NS)
    if meta is not None and meta["gen"] == gen:
        return False
    _manager.destroy(group_name)
    return True


def sync_tree(tree: Optional[Any], group_name: str, *, src_rank: int = 0,
              timeout_ms: int = 120_000):
    """Leaf-wise pytree delivery over ``collective.broadcast`` on an
    EXISTING group — the joiner rejoin path (ISSUE 16): the source rank
    passes its live param/optimizer tree, every other rank passes
    ``None`` (or anything — ignored) and receives the identical tree.
    No checkpoint restore: the tree structure travels as a pickled uint8
    header broadcast, then one broadcast per leaf (transport frames
    carry dtype/shape, the ``serve.weights.broadcast_params`` idiom —
    but over the resizable/declarative group, so rejoin reuses the same
    rendezvous generation the next training round will)."""
    import jax

    rank = get_rank(group_name)
    if rank == src_rank:
        if tree is None:
            raise ValueError("sync_tree source rank must pass the tree")
        host = jax.tree.map(np.asarray, tree)
        leaves, treedef = jax.tree.flatten(host)
        spec = pickle.dumps(treedef)
        broadcast(np.frombuffer(spec, np.uint8), src_rank=src_rank,
                  group_name=group_name, timeout_ms=timeout_ms)
        for leaf in leaves:
            broadcast(np.ascontiguousarray(leaf), src_rank=src_rank,
                      group_name=group_name, timeout_ms=timeout_ms)
        return host
    spec = broadcast(np.empty(0, np.uint8), src_rank=src_rank,
                     group_name=group_name, timeout_ms=timeout_ms)
    treedef = pickle.loads(bytes(spec))
    leaves = [broadcast(np.empty(0, np.uint8), src_rank=src_rank,
                        group_name=group_name, timeout_ms=timeout_ms)
              for _ in range(treedef.num_leaves)]
    return jax.tree.unflatten(treedef, leaves)
