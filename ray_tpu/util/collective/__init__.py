from ray_tpu.util.collective.async_work import CollectiveWork  # noqa: F401
from ray_tpu.util.collective.collective import (  # noqa: F401
    allgather,
    allreduce,
    allreduce_coalesced,
    allreduce_coalesced_async,
    barrier,
    broadcast,
    create_collective_group,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    init_collective_group,
    is_group_initialized,
    recv,
    reduce,
    reducescatter,
    send,
    synchronize,
)
from ray_tpu.util.collective.tp import (  # noqa: F401
    TpOps,
    make_tp_reduce_ops,
    psum_tp_ops,
)
from ray_tpu.util.collective.resizable import (  # noqa: F401
    ResizableGroup,
    refresh_membership,
    sync_tree,
)
from ray_tpu.util.collective.types import (  # noqa: F401
    Backend,
    CollectiveError,
    ReduceOp,
)
