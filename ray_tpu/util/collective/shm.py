"""Same-node collectives over pin-backed shared-memory channels.

The zero-control-plane data path of the "host" backend: when every rank
of a group sits on one node, each rank allocates ONE mutable channel in
the node arena at group-setup time (`channel_create`: create + seal +
pin in one store op, the compiled-DAG pattern from
`_private/channels.py`) and publishes its ``ChannelSpec`` through the
controller KV. After that one-time rendezvous, a steady-state collective
is seqlock rounds over the shared mmap — **zero RPCs**, proven by the
``ray_tpu_rpc_client_calls_total`` counter exactly as the compiled-DAG
suite proves its steady step.

Wire protocol per channel (single writer = the owning rank, world-1
reader slots): every collective posts a tiny packed header round
(dtype/shape/nbytes — validated, so a shape mismatch is a clean error,
never a silent wrong sum), then streams the tensor through the channel
in capacity-sized chunk rounds. Rounds interleave across ranks
(write-mine / read-everyone / ack), so flow control is the channel's own
one-in-flight-step seqlock and memory stays bounded at
``collective_channel_bytes`` per rank regardless of tensor size.

Failure semantics ride the channel machinery: a dead participant's
supervisor closes every channel it touched (its creation pin is
reclaimed through the standard dead-client paths), so blocked peers
raise instead of hanging, and no pin outlives the group.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ray_tpu._private import channels as _channels
from ray_tpu._private import serialization
from ray_tpu._private.exceptions import ChannelClosedError
from ray_tpu._private.ids import ObjectID
from ray_tpu.util.collective import _metrics
from ray_tpu.util.collective import ring as _ring
from ray_tpu.util.collective.types import (CollectiveError, ReduceOp,
                                           check_inplace_out as _check_out,
                                           reduce_ufunc)

logger = logging.getLogger(__name__)


class ShmGroup:
    """Same-node collectives: all-to-all over per-rank arena channels."""

    algo = "shm"

    def __init__(self, core, world_size: int, rank: int, wire_name: str,
                 peers: Dict[int, dict], setup_timeout_ms: int):
        self.world_size = world_size
        self.rank = rank
        self._wire = wire_name
        self._core = core
        self._peers = peers
        self._setup_timeout_ms = setup_timeout_ms
        # explicit p2p (send/recv) rides the chunked worker↔worker
        # transport — the controller is not a mailbox either
        self._t = _ring.P2PTransport(core, wire_name, rank, peers, self.algo)
        # the channel stage builds LAZILY on the first COLLECTIVE: it needs
        # every rank to publish a channel spec, which bystander ranks only
        # do when they reach this point themselves — pairwise send/recv
        # must not block on it (it uses the transport, not the channels)
        self._my_oid: Optional[ObjectID] = None
        self._channels_ready = False
        self._setup_lock = threading.Lock()

    def _ensure_channels(self) -> None:
        if self._channels_ready:
            return
        with self._setup_lock:
            if self._channels_ready:
                return
            self._setup_channels()
            self._channels_ready = True

    def _setup_channels(self) -> None:
        from ray_tpu._private import internal_kv

        core = self._core
        world_size, rank, wire_name = self.world_size, self.rank, self._wire
        cap = max(64, int(core.config.collective_channel_bytes))
        size = _channels.total_size(cap)
        oid = ObjectID.from_put()
        participants = sorted({p["client"] for p in self._peers.values()})
        r = core._run(core.clients.get(core.supervisor_addr).call(
            "channel_create",
            {"channel_id": oid.binary(), "size": size,
             "n_readers": world_size - 1, "participants": participants,
             "client": core._store_client_id,
             "client_addr": core.address},
            timeout=60))
        self._my_oid = oid
        my_spec = _channels.ChannelSpec(
            channel_id=oid.binary(), node_addr=tuple(core.supervisor_addr),
            offset=r["offset"], size=size, n_readers=world_size - 1)
        try:
            internal_kv.kv_put(
                f"{wire_name}:ch:{rank}",
                {"channel_id": my_spec.channel_id, "offset": my_spec.offset,
                 "size": my_spec.size, "n_readers": my_spec.n_readers,
                 "node": core.node_id_hex},
                ns="collective")
            deadline = time.monotonic() + self._setup_timeout_ms / 1000.0
            self._chans: Dict[int, _channels.LocalChannel] = {
                rank: _channels.LocalChannel(core.arena, my_spec)}
            for p in range(world_size):
                if p == rank:
                    continue
                rec = internal_kv.kv_wait(
                    f"{wire_name}:ch:{p}",
                    timeout=max(0.1, deadline - time.monotonic()),
                    ns="collective")
                if rec["node"] != core.node_id_hex:
                    raise CollectiveError(
                        f"collective group {wire_name!r}: rank {p} "
                        f"published a channel on another node — shm algo "
                        f"needs one node")
                spec = _channels.ChannelSpec(
                    channel_id=rec["channel_id"],
                    node_addr=tuple(core.supervisor_addr),
                    offset=rec["offset"], size=rec["size"],
                    n_readers=rec["n_readers"])
                if spec.size != size:
                    raise CollectiveError(
                        f"collective group {wire_name!r}: rank {p} "
                        f"allocated a {spec.size}-byte channel but this "
                        f"rank uses {size} — set "
                        f"RAY_TPU_COLLECTIVE_CHANNEL_BYTES uniformly")
                self._chans[p] = _channels.LocalChannel(core.arena, spec)
        except BaseException:
            # half-built group: hand back the creation pin + close + drop
            # the published spec instead of leaking a pinned arena range
            # per failed setup (the PR-3 mid-compile-unwind lesson)
            self._release_own_channel()
            self._my_oid = None
            raise
        self.capacity = cap
        # per-channel seqlock versions: own advances on write, peers' on
        # read; consistent because every rank runs the same op sequence
        self._wver = 0
        self._rver = {p: 0 for p in self._peers if p != rank}

    # ------------------------------------------------------ round helpers

    def _slot_in(self, p: int) -> int:
        """This rank's reader-ack slot in rank ``p``'s channel header."""
        return self.rank - (1 if self.rank > p else 0)

    def _write(self, payload, deadline: float) -> None:
        self._wver += 2
        try:
            self._chans[self.rank].write(
                payload, self._wver,
                timeout=max(0.05, deadline - time.monotonic()))
        except ChannelClosedError as e:
            raise CollectiveError(
                f"collective group {self._wire!r}: channel closed "
                f"(participant died or group destroyed): {e}") from e
        _metrics.chunks_total.inc(labels=_metrics.labels(self.algo))
        _metrics.bytes_total.inc(len(payload), labels=_metrics.labels(self.algo))

    def _read(self, p: int, deadline: float):
        """One committed round from rank ``p``'s channel; caller must
        ``_ack`` when done with the returned view."""
        self._rver[p] += 2
        try:
            return self._chans[p].read(
                self._rver[p],
                timeout=max(0.05, deadline - time.monotonic()))
        except ChannelClosedError as e:
            raise CollectiveError(
                f"collective group {self._wire!r}: channel of rank {p} "
                f"closed (participant died or group destroyed): {e}") from e

    def _ack(self, p: int) -> None:
        self._chans[p].ack(self._slot_in(p), self._rver[p])

    def _post_header(self, arr: np.ndarray, deadline: float) -> None:
        self._write(serialization.pack(
            (arr.dtype.str, tuple(arr.shape), int(arr.nbytes))), deadline)

    def _read_header(self, p: int, deadline: float) -> tuple:
        view = self._read(p, deadline)
        meta = serialization.unpack(view)  # tiny tuple: copies, safe to ack
        self._ack(p)
        return meta

    def _elems_per_round(self, itemsize: int) -> int:
        return max(1, self.capacity // max(1, itemsize))

    def _others(self) -> List[int]:
        return [p for p in range(self.world_size) if p != self.rank]

    # ------------------------------------------------------------ ops

    def allreduce(self, arr, op: ReduceOp, timeout_ms: int,
                  out: Optional[np.ndarray] = None) -> np.ndarray:
        """``out=`` is the result buffer and MAY alias ``arr`` (the
        donated/in-place form: each chunk is written to the channel
        before peers fold into it, so clobbering the source is safe) —
        a steady-state caller reusing one staging buffer pays zero
        allocations here."""
        self._ensure_channels()
        arr = np.asarray(arr)
        deadline = time.monotonic() + timeout_ms / 1000.0
        src = np.ascontiguousarray(arr)
        if out is None:
            out = src.copy()
        else:
            _check_out(out, src)
            if out is not src:
                np.copyto(out.reshape(-1), src.reshape(-1))
        fold = reduce_ufunc(op)
        with _metrics.round_timer(self.algo):
            self._post_header(src, deadline)
            for p in self._others():
                pd, ps, pn = self._read_header(p, deadline)
                if (pd != src.dtype.str or int(pn) != int(src.nbytes)
                        or tuple(ps) != tuple(src.shape)):
                    raise CollectiveError(
                        f"collective group {self._wire!r}: rank {p} "
                        f"contributed dtype={pd} shape={tuple(ps)}, this "
                        f"rank dtype={src.dtype.str} "
                        f"shape={tuple(src.shape)}")
            src_flat = src.reshape(-1)
            out_flat = out.reshape(-1)
            epr = self._elems_per_round(src.itemsize)
            for start in range(0, src_flat.size, epr):
                stop = min(start + epr, src_flat.size)
                self._write(
                    memoryview(src_flat[start:stop]).cast("B"), deadline)
                for p in self._others():
                    view = self._read(p, deadline)
                    peer = np.frombuffer(view, dtype=src.dtype)
                    seg = out_flat[start:stop]
                    fold(seg, peer, out=seg)
                    self._ack(p)
        _metrics.ops_total.inc(labels=_metrics.labels(self.algo))
        if op is ReduceOp.MEAN:
            if np.issubdtype(out.dtype, np.inexact):
                np.divide(out, self.world_size, out=out)
                return out
            return out / self.world_size  # integer mean widens to float
        return out

    def reduce(self, arr, op: ReduceOp, root_rank: int, timeout_ms: int):
        out = self.allreduce(arr, op, timeout_ms)
        return out if self.rank == root_rank else np.asarray(arr)

    def broadcast(self, arr, root_rank: int, timeout_ms: int) -> np.ndarray:
        self._ensure_channels()
        deadline = time.monotonic() + timeout_ms / 1000.0
        with _metrics.round_timer(self.algo):
            if self.rank == root_rank:
                src = np.ascontiguousarray(np.asarray(arr))
                self._post_header(src, deadline)
                flat = src.reshape(-1)
                epr = self._elems_per_round(src.itemsize)
                for start in range(0, flat.size, epr):
                    stop = min(start + epr, flat.size)
                    self._write(
                        memoryview(flat[start:stop]).cast("B"), deadline)
                out = np.asarray(arr)
            else:
                dt, shape, total = self._read_header(root_rank, deadline)
                out = np.empty(shape, dtype=np.dtype(dt))
                raw = memoryview(out.reshape(-1)).cast("B")
                epr = self._elems_per_round(out.itemsize)
                chunk_bytes = epr * out.itemsize
                for pos in range(0, int(total), chunk_bytes):
                    view = self._read(root_rank, deadline)
                    raw[pos:pos + len(view)] = view
                    self._ack(root_rank)
        _metrics.ops_total.inc(labels=_metrics.labels(self.algo))
        return out

    def allgather(self, arr, timeout_ms: int) -> List[np.ndarray]:
        self._ensure_channels()
        deadline = time.monotonic() + timeout_ms / 1000.0
        src = np.ascontiguousarray(np.asarray(arr))
        results: List[Optional[np.ndarray]] = [None] * self.world_size
        results[self.rank] = np.asarray(arr)
        with _metrics.round_timer(self.algo):
            self._post_header(src, deadline)
            metas = {p: self._read_header(p, deadline)
                     for p in self._others()}
            outs: Dict[int, np.ndarray] = {}
            raws: Dict[int, memoryview] = {}
            rounds = 0
            for p, (dt, shape, total) in metas.items():
                outs[p] = np.empty(shape, dtype=np.dtype(dt))
                raws[p] = memoryview(outs[p].reshape(-1)).cast("B")
                epr = self._elems_per_round(outs[p].itemsize)
                rounds = max(rounds,
                             -(-int(total) // (epr * outs[p].itemsize)))
            flat = src.reshape(-1)
            epr = self._elems_per_round(src.itemsize)
            my_rounds = -(-flat.size // epr) if flat.size else 0
            rounds = max(rounds, my_rounds)
            pos: Dict[int, int] = {p: 0 for p in self._others()}
            # interleaved rounds (ragged-tolerant): write my chunk k, read
            # every peer still streaming — all-write-then-read would
            # deadlock on the one-step channel backpressure
            for k in range(rounds):
                if k < my_rounds:
                    start = k * epr
                    stop = min(start + epr, flat.size)
                    self._write(
                        memoryview(flat[start:stop]).cast("B"), deadline)
                for p in self._others():
                    if pos[p] >= len(raws[p]) and len(raws[p]) > 0:
                        continue
                    if len(raws[p]) == 0:
                        continue
                    view = self._read(p, deadline)
                    raws[p][pos[p]:pos[p] + len(view)] = view
                    pos[p] += len(view)
                    self._ack(p)
            for p in self._others():
                results[p] = outs[p]
        _metrics.ops_total.inc(labels=_metrics.labels(self.algo))
        return list(results)

    def reducescatter(self, arr, op: ReduceOp, timeout_ms: int) -> np.ndarray:
        """Each rank folds ONLY its own axis-0 split while streaming
        peers' rounds (reads outside the split are acked untouched) —
        O(N/world) copy+compute per rank instead of reduce-everything."""
        self._ensure_channels()
        arr = np.asarray(arr)
        deadline = time.monotonic() + timeout_ms / 1000.0
        src = np.ascontiguousarray(arr)
        fold = reduce_ufunc(op)
        # my split in flat element space (axis-0 splits of a contiguous
        # array are contiguous flat ranges)
        splits = np.array_split(src, self.world_size, axis=0)
        row_elems = int(np.prod(src.shape[1:], dtype=np.int64)) \
            if src.ndim > 1 else 1
        rows_before = sum(s.shape[0] for s in splits[:self.rank])
        seg_lo = rows_before * row_elems
        seg_hi = seg_lo + splits[self.rank].size
        mine = splits[self.rank].copy()
        mine_flat = mine.reshape(-1)
        with _metrics.round_timer(self.algo):
            self._post_header(src, deadline)
            for p in self._others():
                pd, ps, pn = self._read_header(p, deadline)
                if (pd != src.dtype.str or int(pn) != int(src.nbytes)
                        or tuple(ps) != tuple(src.shape)):
                    raise CollectiveError(
                        f"collective group {self._wire!r}: rank {p} "
                        f"reducescatter mismatch: dtype={pd} "
                        f"shape={tuple(ps)} vs dtype={src.dtype.str} "
                        f"shape={tuple(src.shape)}")
            src_flat = src.reshape(-1)
            epr = self._elems_per_round(src.itemsize)
            for start in range(0, src_flat.size, epr):
                stop = min(start + epr, src_flat.size)
                self._write(
                    memoryview(src_flat[start:stop]).cast("B"), deadline)
                lo = max(start, seg_lo)
                hi = min(stop, seg_hi)
                for p in self._others():
                    view = self._read(p, deadline)
                    if lo < hi:
                        peer = np.frombuffer(view, dtype=src.dtype)
                        seg = mine_flat[lo - seg_lo:hi - seg_lo]
                        fold(seg, peer[lo - start:hi - start], out=seg)
                    self._ack(p)
        _metrics.ops_total.inc(labels=_metrics.labels(self.algo))
        if op is ReduceOp.MEAN:
            return mine / self.world_size
        return mine

    def barrier(self, timeout_ms: int) -> None:
        self.allreduce(np.zeros((1,), np.float32), ReduceOp.SUM, timeout_ms)

    def send(self, arr, dst_rank: int, timeout_ms: int) -> None:
        self._t.send(dst_rank, np.asarray(arr),
                     time.monotonic() + timeout_ms / 1000.0)

    def recv(self, src_rank: int, timeout_ms: int) -> np.ndarray:
        return self._t.recv(src_rank,
                            time.monotonic() + timeout_ms / 1000.0)

    def _release_own_channel(self) -> None:
        """Best-effort close + unpin + unpublish of this rank's channel
        (both the destroy path and the half-built-setup unwind)."""
        from ray_tpu._private import internal_kv

        core = self._core
        try:
            core._run(core.clients.get(core.supervisor_addr).call(
                "channel_close", {"channel_id": self._my_oid.binary()},
                timeout=10))
        except Exception:
            pass
        try:
            # hand back the creation pin so the channel range can be freed
            core._run(core.clients.get(core.supervisor_addr).call(
                "store_unpin",
                {"object_id": self._my_oid.binary(),
                 "client": core._store_client_id}, timeout=10))
        except Exception:
            pass
        try:
            internal_kv.kv_del(f"{self._wire}:ch:{self.rank}",
                               ns="collective")
        except Exception:
            pass

    def destroy(self) -> None:
        if self._my_oid is not None:
            self._release_own_channel()
        self._t.close()
