"""Shared types for the collective API.

Analog of the reference's `python/ray/util/collective/types.py` (ReduceOp,
backend enums, option dataclasses) — re-based for TPU: the fast backend is
XLA collectives over ICI ("xla"), not NCCL; the slow/control backend is the
controller-KV rendezvous ("host"), not Gloo.
"""

from __future__ import annotations

import enum


class Backend(str, enum.Enum):
    #: XLA collectives over ICI/DCN — jax.distributed runtime + mesh psum.
    XLA = "xla"
    #: Controller-KV rendezvous over the control plane (gloo analog): works
    #: anywhere, sized for control-plane payloads (weights broadcast, metrics),
    #: not the tensor hot path.
    HOST = "host"

    @classmethod
    def parse(cls, v) -> "Backend":
        if isinstance(v, Backend):
            return v
        v = str(v).lower()
        if v in ("xla", "ici", "tpu"):
            return cls.XLA
        if v in ("host", "cpu", "gloo", "kv"):
            return cls.HOST
        raise ValueError(f"unknown collective backend {v!r}; use 'xla' or 'host'")


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "prod"
    MAX = "max"
    MIN = "min"
    MEAN = "mean"


class CollectiveError(RuntimeError):
    """A collective failed cleanly (peer death, membership change, oversize
    payload) — never a silently wrong result."""


def reduce_ufunc(op: ReduceOp):
    """Elementwise pairwise accumulator for streaming reductions (ring
    segments, shared-memory chunk rounds). MEAN accumulates with add;
    callers divide by world_size once at the end."""
    import numpy as np

    return {
        ReduceOp.SUM: np.add,
        ReduceOp.MEAN: np.add,
        ReduceOp.PRODUCT: np.multiply,
        ReduceOp.MAX: np.maximum,
        ReduceOp.MIN: np.minimum,
    }[op]
