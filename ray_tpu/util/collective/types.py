"""Shared types for the collective API.

Analog of the reference's `python/ray/util/collective/types.py` (ReduceOp,
backend enums, option dataclasses) — re-based for TPU: the fast backend is
XLA collectives over ICI ("xla"), not NCCL; the slow/control backend is the
controller-KV rendezvous ("host"), not Gloo.
"""

from __future__ import annotations

import enum


class Backend(str, enum.Enum):
    #: XLA collectives over ICI/DCN — jax.distributed runtime + mesh psum.
    XLA = "xla"
    #: Controller-KV rendezvous over the control plane (gloo analog): works
    #: anywhere, sized for control-plane payloads (weights broadcast, metrics),
    #: not the tensor hot path.
    HOST = "host"

    @classmethod
    def parse(cls, v) -> "Backend":
        if isinstance(v, Backend):
            return v
        v = str(v).lower()
        if v in ("xla", "ici", "tpu"):
            return cls.XLA
        if v in ("host", "cpu", "gloo", "kv"):
            return cls.HOST
        raise ValueError(f"unknown collective backend {v!r}; use 'xla' or 'host'")


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "prod"
    MAX = "max"
    MIN = "min"
    MEAN = "mean"


class CollectiveError(RuntimeError):
    """A collective failed cleanly (peer death, membership change, oversize
    payload) — never a silently wrong result."""


def reduce_ufunc(op: ReduceOp):
    """Elementwise pairwise accumulator for streaming reductions (ring
    segments, shared-memory chunk rounds). MEAN accumulates with add;
    callers divide by world_size once at the end."""
    import numpy as np

    return {
        ReduceOp.SUM: np.add,
        ReduceOp.MEAN: np.add,
        ReduceOp.PRODUCT: np.multiply,
        ReduceOp.MAX: np.maximum,
        ReduceOp.MIN: np.minimum,
    }[op]


def check_inplace_out(out, src) -> None:
    """Validate an impl-level ``out=`` result buffer (which may alias the
    input): it must be a C-contiguous ndarray of the input's dtype and
    byte size. A non-contiguous buffer would make ``out.reshape(-1)``
    a DETACHED copy — the reduce would land in a temp and the caller's
    array would stay silently stale."""
    import numpy as np

    if not isinstance(out, np.ndarray) or not out.flags.c_contiguous:
        raise ValueError(
            "collective out= buffer must be a C-contiguous ndarray")
    if out.dtype != src.dtype or out.nbytes != src.nbytes:
        raise ValueError(
            f"collective out= buffer is {out.dtype}/{out.nbytes}B but the "
            f"input is {src.dtype}/{src.nbytes}B")


def prescale_factor(op: ReduceOp, dtype, world_size: int):
    """The per-rank pre-scale that turns a MEAN into a plain SUM.

    A coalesced MEAN scales each contribution by ``1/world`` while packing
    it into the staging buffer (a multiply fused into a copy that happens
    anyway) and then reduces with SUM — so no post-reduce ``out / world``
    pass, which on a gradient tree was one full extra tree copy per step.
    Returns ``None`` when the op isn't MEAN or the dtype can't carry the
    scale (integer means fall back to SUM + one divide at unpack)."""
    import numpy as np

    if op is not ReduceOp.MEAN:
        return None
    if not np.issubdtype(np.dtype(dtype), np.inexact):
        return None
    return 1.0 / float(world_size)
