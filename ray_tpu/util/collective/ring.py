"""Peer-to-peer chunked collective transport + ring algorithms.

The cross-node data path of the "host" backend: after a one-time
controller-KV rendezvous (addresses only — the controller never carries
tensor bytes), ranks exchange tensor segments over DIRECT worker↔worker
RPCs. Each logical message streams as chunked, bounded-window frames
(`rpc.call_chunked`, the `RAY_TPU_OBJECT_TRANSFER_WINDOW` shape from the
object data plane), so tensors larger than the RPC `MAX_FRAME` work and
a slow link never buffers a whole tensor.

Allreduce is the classic ring: a reduce-scatter phase (world-1 rounds,
each rank sends one segment to its right neighbor and folds the segment
arriving from its left) followed by an allgather phase passing the fully
reduced segments around. Per-link traffic is O(2·N·(W-1)/W) ≈ O(N) —
independent of world size — versus the old controller-KV rounds moving
O(N·W) through one pickled control-plane socket.

Frames are idempotent (absolute byte offsets into a per-message buffer),
so the RPC layer's transparent drop/dup/retry handling converges without
a replay cache; a mid-ring participant death surfaces as a clean
``TimeoutError`` / ``CollectiveError`` (node deaths fail fast through the
core worker's node-death fan-out), never a wrong sum.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.util.collective import _metrics
from ray_tpu.util.collective.types import (CollectiveError, ReduceOp,
                                           check_inplace_out as _check_out,
                                           reduce_ufunc)

logger = logging.getLogger(__name__)


# ------------------------------------------------------------------ inbox


class _Inbox:
    """Per-process landing zone for ``collective_chunk`` frames.

    Messages are keyed ``(group, src_rank, seq)`` where ``seq`` counts
    messages per directed (src → this process) pair — both endpoints
    advance the counter in lockstep because collective call order is the
    same on every rank (the standard requirement). Chunks land at
    absolute offsets; duplicates (chaos dup, transparent RPC retries) are
    dropped by offset, and frames at or below the per-pair completion
    watermark (a late duplicate of an already-consumed message) are
    dropped entirely so they can never strand a stale buffer.
    """

    def __init__(self):
        self._cond = threading.Condition()
        self._msgs: Dict[tuple, dict] = {}
        self._watermark: Dict[tuple, int] = {}
        self._dead_nodes: set = set()
        # wire names of destroyed groups (insertion-ordered dict used as
        # a bounded set: a process that churns thousands of groups must
        # not grow this forever — evicting the OLDEST tombstone is safe,
        # its straggler frames have long since stopped arriving)
        self._closed: Dict[str, bool] = {}

    # runs on the core IO loop (sync RPC handler): dict updates + one
    # bounded memcpy per frame
    def deliver(self, body: dict) -> None:
        key = (body["group"], body["src"], body["seq"])
        with self._cond:
            if body["group"] in self._closed:
                return  # late frame for a destroyed group: drop, don't buffer
            if body["seq"] <= self._watermark.get(key[:2], -1):
                return
            ent = self._msgs.get(key)
            if ent is None:
                ent = {
                    "buf": bytearray(body["total"]),
                    "got": set(),
                    "remaining": body["total"],
                    "dtype": body["dtype"],
                    "shape": tuple(body["shape"]),
                }
                self._msgs[key] = ent
            off = body["offset"]
            if off in ent["got"]:
                return
            data = body["data"]
            ent["buf"][off:off + len(data)] = data
            ent["got"].add(off)
            ent["remaining"] -= len(data)
            if ent["remaining"] <= 0:
                self._cond.notify_all()

    def wait(self, group: str, src: int, seq: int, deadline: float,
             peer_node: str = "") -> np.ndarray:
        key = (group, src, seq)
        with self._cond:
            while True:
                if group in self._closed:
                    # a destroy with work in flight must unpark blocked
                    # waiters NOW, not after the full collective timeout
                    raise CollectiveError(
                        f"collective group {group!r} was destroyed while "
                        f"waiting for message {seq} from rank {src}")
                ent = self._msgs.get(key)
                if ent is not None and ent["remaining"] <= 0:
                    del self._msgs[key]
                    self._watermark[(group, src)] = seq
                    arr = np.frombuffer(
                        ent["buf"], dtype=np.dtype(ent["dtype"]))
                    return arr.reshape(ent["shape"])
                if peer_node and peer_node in self._dead_nodes:
                    raise CollectiveError(
                        f"collective group {group!r}: peer rank {src} is "
                        f"on dead node {peer_node[:12]}")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"collective group {group!r}: timed out waiting "
                        f"for message {seq} from rank {src}")
                self._cond.wait(min(remaining, 0.5))

    def mark_node_dead(self, node_id_hex: str) -> None:
        with self._cond:
            self._dead_nodes.add(node_id_hex)
            self._cond.notify_all()

    def forget(self, group: str) -> None:
        """Drop this group's message state and tombstone the wire name so
        parked waiters raise instead of burning their full timeout (wire
        names are incarnation-suffixed — a re-created group never
        collides with its predecessor's tombstone)."""
        with self._cond:
            for key in [k for k in self._msgs if k[0] == group]:
                del self._msgs[key]
            for key in [k for k in self._watermark if k[0] == group]:
                del self._watermark[key]
            self._closed[group] = True
            while len(self._closed) > 256:
                self._closed.pop(next(iter(self._closed)))
            self._cond.notify_all()


_REGISTER_LOCK = threading.Lock()


def ensure_registered(core) -> _Inbox:
    """Install the p2p collective transport on this process's core worker
    (idempotent; safe under concurrent group inits from user threads —
    without the lock two racing callers could each build an _Inbox and
    one group would wait forever on the instance the handler never feeds).
    Workers do this at startup (`default_worker.main`); driver processes
    that join a group do it lazily at group init — a rank only publishes
    its address AFTER this ran, so no frame can ever arrive unroutable."""
    inbox = getattr(core, "_collective_inbox", None)
    if inbox is not None:
        return inbox
    with _REGISTER_LOCK:
        inbox = getattr(core, "_collective_inbox", None)
        if inbox is not None:
            return inbox
        inbox = _Inbox()

        def _collective_chunk(body):
            inbox.deliver(body)
            return True

        _collective_chunk._rpc_idempotent = True  # offset-keyed: dup safe
        core.server.register("collective_chunk", _collective_chunk)
        # a dead NODE fails ring waits immediately instead of burning the
        # full collective timeout (worker-level deaths still time out)
        core.node_death_hooks.append(
            lambda node_hex, addr: inbox.mark_node_dead(node_hex))
        core._collective_inbox = inbox
    return inbox


# -------------------------------------------------------------- transport


class P2PTransport:
    """Directed tensor messaging between the ranks of one group."""

    def __init__(self, core, wire_name: str, rank: int,
                 peers: Dict[int, dict], algo: str):
        self._core = core
        self._wire = wire_name
        self._rank = rank
        self._peers = peers
        self._algo = algo
        self._send_seq: Dict[int, int] = {}
        self._recv_seq: Dict[int, int] = {}
        self._inbox = ensure_registered(core)

    def send(self, dst: int, arr: np.ndarray, deadline: float) -> None:
        from ray_tpu._private import rpc

        arr = np.ascontiguousarray(arr)
        data = memoryview(arr.reshape(-1)).cast("B")
        seq = self._send_seq.get(dst, 0)
        self._send_seq[dst] = seq + 1
        timeout = deadline - time.monotonic()
        if timeout <= 0:
            raise TimeoutError(
                f"collective group {self._wire!r}: send to rank {dst} "
                f"has no time budget left")
        cfg = self._core.config
        base = {"group": self._wire, "src": self._rank, "seq": seq,
                "total": data.nbytes, "dtype": arr.dtype.str,
                "shape": tuple(arr.shape)}
        client = self._core.clients.get(tuple(self._peers[dst]["addr"]))
        try:
            frames = self._core._run(
                rpc.call_chunked(
                    client, "collective_chunk", base, data,
                    chunk_bytes=cfg.collective_chunk_bytes,
                    window=cfg.collective_window, timeout=timeout),
                timeout=timeout + 10)
        except (TimeoutError, CollectiveError):
            raise
        except Exception as e:  # noqa: BLE001 — transport failure = peer gone
            raise CollectiveError(
                f"collective group {self._wire!r}: peer rank {dst} at "
                f"{tuple(self._peers[dst]['addr'])} unreachable: {e!r}"
            ) from e
        _metrics.chunks_total.inc(frames, labels=_metrics.labels(self._algo))
        _metrics.bytes_total.inc(data.nbytes, labels=_metrics.labels(self._algo))

    def recv(self, src: int, deadline: float) -> np.ndarray:
        seq = self._recv_seq.get(src, 0)
        self._recv_seq[src] = seq + 1
        return self._inbox.wait(
            self._wire, src, seq, deadline,
            peer_node=self._peers[src].get("node", ""))

    def close(self) -> None:
        self._inbox.forget(self._wire)


# ------------------------------------------------------------- ring group


def _seg_slices(n: int, world: int) -> List[slice]:
    """np.array_split boundaries over ``n`` flat elements."""
    base, extra = divmod(n, world)
    out, pos = [], 0
    for i in range(world):
        size = base + (1 if i < extra else 0)
        out.append(slice(pos, pos + size))
        pos += size
    return out


class RingGroup:
    """Cross-node collectives: ring reduce-scatter + allgather over the
    chunked p2p transport. The controller carried the rendezvous and
    nothing else — every tensor byte moves worker↔worker."""

    algo = "ring"

    def __init__(self, core, world_size: int, rank: int, wire_name: str,
                 peers: Dict[int, dict]):
        self.world_size = world_size
        self.rank = rank
        self._wire = wire_name
        self._t = P2PTransport(core, wire_name, rank, peers, self.algo)

    # neighbors
    @property
    def _right(self) -> int:
        return (self.rank + 1) % self.world_size

    @property
    def _left(self) -> int:
        return (self.rank - 1) % self.world_size

    def _deadline(self, timeout_ms: int) -> float:
        return time.monotonic() + timeout_ms / 1000.0

    def _check_incoming(self, incoming: np.ndarray, expect_size: int,
                        dtype, what: str) -> np.ndarray:
        """A mis-sized peer segment must be a clean error: numpy would
        happily BROADCAST a size-1 segment across a fold (a silently
        wrong sum), and silently cast a dtype mismatch."""
        if incoming.size != expect_size or incoming.dtype != dtype:
            raise CollectiveError(
                f"collective group {self._wire!r}: {what} from rank "
                f"{self._left} has size={incoming.size} "
                f"dtype={incoming.dtype}, expected size={expect_size} "
                f"dtype={dtype} — all ranks must pass same-shape, "
                f"same-dtype tensors")
        return incoming

    def allreduce(self, arr, op: ReduceOp, timeout_ms: int,
                  out: Optional[np.ndarray] = None) -> np.ndarray:
        """``out=`` is the result buffer and MAY alias ``arr`` (the ring
        already reduces in place over its working copy; donating the
        input just skips that copy)."""
        arr = np.asarray(arr)
        w, r = self.world_size, self.rank
        deadline = self._deadline(timeout_ms)
        src = np.ascontiguousarray(arr)
        if out is None:
            out = src.copy()
        else:
            _check_out(out, src)
            if out is not src:
                np.copyto(out.reshape(-1), src.reshape(-1))
        flat = out.reshape(-1)
        segs = _seg_slices(flat.size, w)
        fold = reduce_ufunc(op)
        with _metrics.round_timer(self.algo):
            # phase 1: reduce-scatter — after w-1 rounds rank r fully owns
            # segment (r+1) % w
            for t in range(w - 1):
                send_i = (r - t) % w
                recv_i = (r - t - 1) % w
                self._t.send(self._right, flat[segs[send_i]], deadline)
                incoming = self._check_incoming(
                    self._t.recv(self._left, deadline),
                    segs[recv_i].stop - segs[recv_i].start, out.dtype,
                    "reduce-scatter segment")
                seg = flat[segs[recv_i]]
                fold(seg, incoming, out=seg)
            # phase 2: allgather the reduced segments
            for t in range(w - 1):
                send_i = (r + 1 - t) % w
                recv_i = (r - t) % w
                self._t.send(self._right, flat[segs[send_i]], deadline)
                flat[segs[recv_i]] = self._check_incoming(
                    self._t.recv(self._left, deadline),
                    segs[recv_i].stop - segs[recv_i].start, out.dtype,
                    "allgather segment").reshape(-1)
        _metrics.ops_total.inc(labels=_metrics.labels(self.algo))
        if op is ReduceOp.MEAN:
            if np.issubdtype(out.dtype, np.inexact):
                np.divide(out, w, out=out)
                return out
            return (out / w).reshape(arr.shape)  # integer mean widens
        return out

    def reduce(self, arr, op: ReduceOp, root_rank: int, timeout_ms: int):
        out = self.allreduce(arr, op, timeout_ms)
        return out if self.rank == root_rank else np.asarray(arr)

    def broadcast(self, arr, root_rank: int, timeout_ms: int) -> np.ndarray:
        w, r = self.world_size, self.rank
        deadline = self._deadline(timeout_ms)
        with _metrics.round_timer(self.algo):
            if r == root_rank:
                out = np.asarray(arr)
                if w > 1:
                    self._t.send(self._right, out, deadline)
            else:
                # relay around the ring; the frame carries dtype/shape, so
                # non-root ranks need no local template tensor
                out = self._t.recv(self._left, deadline)
                if self._right != root_rank:
                    self._t.send(self._right, out, deadline)
        _metrics.ops_total.inc(labels=_metrics.labels(self.algo))
        return out

    def allgather(self, arr, timeout_ms: int) -> List[np.ndarray]:
        w, r = self.world_size, self.rank
        deadline = self._deadline(timeout_ms)
        pieces: List[Optional[np.ndarray]] = [None] * w
        pieces[r] = np.asarray(arr)
        with _metrics.round_timer(self.algo):
            for t in range(w - 1):
                self._t.send(self._right, pieces[(r - t) % w], deadline)
                pieces[(r - t - 1) % w] = self._t.recv(self._left, deadline)
        _metrics.ops_total.inc(labels=_metrics.labels(self.algo))
        return list(pieces)

    def reducescatter(self, arr, op: ReduceOp, timeout_ms: int) -> np.ndarray:
        """Real reduce-scatter: ONLY the reduce-scatter phase plus one
        hop to land each rank's own axis-0 split — O(N) per link, no
        full-tensor allgather tail."""
        w, r = self.world_size, self.rank
        if w == 1:
            return np.asarray(arr)
        deadline = self._deadline(timeout_ms)
        acc = np.ascontiguousarray(np.asarray(arr)).copy()
        segs = np.array_split(acc, w, axis=0)  # views into acc
        fold = reduce_ufunc(op)
        with _metrics.round_timer(self.algo):
            for t in range(w - 1):
                send_i = (r - t) % w
                recv_i = (r - t - 1) % w
                self._t.send(self._right, segs[send_i], deadline)
                incoming = self._check_incoming(
                    self._t.recv(self._left, deadline),
                    segs[recv_i].size, acc.dtype, "reducescatter segment")
                fold(segs[recv_i], incoming.reshape(segs[recv_i].shape),
                     out=segs[recv_i])
            # rank r now owns fully reduced segment (r+1) % w; its own
            # split (index r) is owned by its left neighbor — one hop
            self._t.send(self._right, segs[(r + 1) % w], deadline)
            mine = self._check_incoming(
                self._t.recv(self._left, deadline), segs[r].size,
                acc.dtype, "reducescatter result").reshape(segs[r].shape)
        _metrics.ops_total.inc(labels=_metrics.labels(self.algo))
        if op is ReduceOp.MEAN:
            return mine / w
        return mine

    def barrier(self, timeout_ms: int) -> None:
        self.allreduce(np.zeros((1,), np.float32), ReduceOp.SUM, timeout_ms)

    def send(self, arr, dst_rank: int, timeout_ms: int) -> None:
        self._t.send(dst_rank, np.asarray(arr), self._deadline(timeout_ms))

    def recv(self, src_rank: int, timeout_ms: int) -> np.ndarray:
        return self._t.recv(src_rank, self._deadline(timeout_ms))

    def destroy(self) -> None:
        self._t.close()
