"""Shared instrumentation for the host-backend collective data paths.

One definition site for the counters every algorithm (shm / ring / kv)
reports into — keeping the shm and kv paths free of any dependency on
the ring transport module.
"""

from __future__ import annotations

import contextlib
from typing import Dict

from ray_tpu._private import flight
from ray_tpu._private.metrics import Counter, Gauge, Histogram

ops_total = Counter(
    "ray_tpu_collective_ops_total",
    "Collective operations completed in this process, by algo/backend")
bytes_total = Counter(
    "ray_tpu_collective_bytes_total",
    "Collective payload bytes moved by this process, by algo/backend")
chunks_total = Counter(
    "ray_tpu_collective_chunks_total",
    "Collective transfer frames/chunk rounds issued, by algo/backend")
round_seconds = Histogram(
    "ray_tpu_collective_round_seconds",
    "Wall-clock seconds per collective call, by algo")
# ---- async overlap (allreduce_coalesced_async / CollectiveWork) ----
overlap_rounds_total = Counter(
    "ray_tpu_collective_overlap_rounds_total",
    "Bucket rounds reduced by the async overlap runner, by algo/backend "
    "(zero means every coalesced call took the synchronous path)")
wait_seconds = Histogram(
    "ray_tpu_collective_wait_seconds",
    "Wall-clock seconds callers block in CollectiveWork.wait() — compare "
    "against ray_tpu_collective_round_seconds for the overlap fraction")
staging_bytes = Gauge(
    "ray_tpu_collective_staging_bytes",
    "Bytes held in the overlap runner's persistent staging-buffer pool "
    "(flat after warmup = steady state allocates nothing)")
staging_allocs_total = Counter(
    "ray_tpu_collective_staging_allocs_total",
    "Staging buffer allocations by the overlap runner (stops moving once "
    "the pool serves every bucket)")


def labels(algo: str) -> Dict[str, str]:
    return {"algo": algo, "backend": "host"}


_flight_round_ids: Dict[str, int] = {}


@contextlib.contextmanager
def round_timer(algo: str):
    """``round_seconds`` histogram + a per-algo flight-recorder span
    (``col.shm_round`` / ``col.ring_round`` / ``col.kv_round``) around one
    collective call — the histogram averages, the span shows WHERE in the
    iteration the round sat."""
    nid = _flight_round_ids.get(algo)
    if nid is None:
        nid = flight.intern(f"col.{algo}_round")
        _flight_round_ids[algo] = nid
    t0 = flight.now()
    try:
        with round_seconds.time(labels={"algo": algo}):
            yield
    finally:
        # record failed rounds too (the histogram does): a round that
        # times out on peer death is the stall the timeline is FOR
        flight.span_since(nid, t0)
