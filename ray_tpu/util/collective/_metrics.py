"""Shared instrumentation for the host-backend collective data paths.

One definition site for the counters every algorithm (shm / ring / kv)
reports into — keeping the shm and kv paths free of any dependency on
the ring transport module.
"""

from __future__ import annotations

from typing import Dict

from ray_tpu._private.metrics import Counter, Histogram

ops_total = Counter(
    "ray_tpu_collective_ops_total",
    "Collective operations completed in this process, by algo/backend")
bytes_total = Counter(
    "ray_tpu_collective_bytes_total",
    "Collective payload bytes moved by this process, by algo/backend")
chunks_total = Counter(
    "ray_tpu_collective_chunks_total",
    "Collective transfer frames/chunk rounds issued, by algo/backend")
round_seconds = Histogram(
    "ray_tpu_collective_round_seconds",
    "Wall-clock seconds per collective call, by algo")


def labels(algo: str) -> Dict[str, str]:
    return {"algo": algo, "backend": "host"}
