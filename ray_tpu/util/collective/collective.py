"""Collective communication API between tasks/actors.

API shaped like the reference's `ray.util.collective.collective`
(`python/ray/util/collective/collective.py:120-655`: init_collective_group,
create_collective_group, allreduce :258, reduce :311, broadcast :373,
allgather :423, reducescatter :472, send :531, recv :594, barrier), re-based
for TPU:

  * backend "xla" ≈ the reference's NCCL group — but instead of explicit
    device-to-device NCCL calls, ranks join one `jax.distributed` runtime and
    every collective is a jitted XLA program over a one-axis device mesh, so
    the bytes ride ICI/DCN exactly as GSPMD would move them.
  * backend "host" ≈ the reference's Gloo group — a controller-KV rendezvous
    over the control plane. Works between any processes with no device
    requirements; sized for control-plane payloads (weight broadcast at init,
    metrics reduction), not the tensor hot path. The tensor hot path in this
    framework is mesh-sharded jit (see ray_tpu.parallel), which needs no
    explicit collective calls at all.

Both imperative (`init_collective_group` inside each worker) and declarative
(`create_collective_group` from the driver over actor handles) setup are
supported, mirroring collective.py:120/:151.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.util.collective.types import Backend, ReduceOp

logger = logging.getLogger(__name__)

_KV_NS = "collective"


def _kv():
    from ray_tpu._private import internal_kv

    return internal_kv


def _node_ip() -> str:
    """Best reachable address of this host for cross-host rendezvous."""
    import socket

    ip = os.environ.get("RAY_TPU_NODE_IP")
    if ip:
        return ip
    try:
        # UDP connect picks the outbound interface without sending a packet.
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            ip = s.getsockname()[0]
        finally:
            s.close()
        if ip and not ip.startswith("127."):
            return ip
    except OSError:
        pass
    try:
        ip = socket.gethostbyname(socket.gethostname())
        if ip and not ip.startswith("127."):
            return ip
    except OSError:
        pass
    return "127.0.0.1"


# --------------------------------------------------------------------- groups


class BaseGroup:
    def __init__(self, world_size: int, rank: int, group_name: str):
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} out of range for world_size {world_size}")
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self._decl_gen = None  # set when created from declarative KV metadata

    def destroy(self) -> None:
        pass


def _reduce_fn(op: ReduceOp):
    return {
        ReduceOp.SUM: lambda a: a.sum(axis=0),
        ReduceOp.MEAN: lambda a: a.mean(axis=0),
        ReduceOp.PRODUCT: lambda a: a.prod(axis=0),
        ReduceOp.MAX: lambda a: a.max(axis=0),
        ReduceOp.MIN: lambda a: a.min(axis=0),
    }[op]


class HostGroup(BaseGroup):
    """Control-plane collectives over the controller KV (gloo analog).

    Protocol: every collective call gets a per-group sequence number (all
    ranks call collectives in the same order — the standard requirement).
    Ranks post contributions under ``{group}:{seq}:c:{rank}``; rank 0 reduces
    and posts ``{group}:{seq}:r``; ranks poll for the result. Rank 0 deletes
    the previous call's result right before posting the next one — safe,
    because holding every contribution of call N implies every rank has read
    the result of call N-1.
    """

    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        self._seq = 0
        self._p2p_seq: Dict[tuple, int] = {}

    # ----- kv plumbing

    def _key(self, seq: int, kind: str, rank: Optional[int] = None) -> str:
        k = f"{self.group_name}:{seq}:{kind}"
        return k if rank is None else f"{k}:{rank}"

    def _poll(self, key: str, timeout_ms: int, delete: bool = False):
        kv = _kv()
        deadline = time.monotonic() + timeout_ms / 1000.0
        pause = 0.001
        while True:
            val = kv.kv_get(key, ns=_KV_NS)
            if val is not None:
                if delete:
                    kv.kv_del(key, ns=_KV_NS)
                return val
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"collective group {self.group_name!r} rank {self.rank}: "
                    f"timed out waiting for {key!r}"
                )
            time.sleep(pause)
            pause = min(pause * 1.5, 0.05)

    def _round(self, payload, combine, timeout_ms: int):
        """One gather-to-root + broadcast round; returns the combined result."""
        kv = _kv()
        seq, self._seq = self._seq, self._seq + 1
        if self.rank == 0:
            # rank 0 is the reducer: its own contribution never needs to
            # transit the controller — use the local payload in place.
            parts = [
                payload if r == 0 else
                self._poll(self._key(seq, "c", r), timeout_ms, delete=True)
                for r in range(self.world_size)
            ]
            result = combine(parts)
            if seq > 0:
                kv.kv_del(self._key(seq - 1, "r"), ns=_KV_NS)
            kv.kv_put(self._key(seq, "r"), result, ns=_KV_NS)
            return result
        kv.kv_put(self._key(seq, "c", self.rank), payload, ns=_KV_NS)
        return self._poll(self._key(seq, "r"), timeout_ms)

    # ----- ops

    def allreduce(self, arr: np.ndarray, op: ReduceOp, timeout_ms: int) -> np.ndarray:
        fn = _reduce_fn(op)
        return self._round(
            np.asarray(arr), lambda parts: fn(np.stack(parts)), timeout_ms
        )

    def reduce(self, arr, op: ReduceOp, root_rank: int, timeout_ms: int):
        out = self.allreduce(arr, op, timeout_ms)
        return out if self.rank == root_rank else np.asarray(arr)

    def broadcast(self, arr, root_rank: int, timeout_ms: int):
        # Non-root ranks post a tiny marker instead of their full tensor: only
        # root's contribution is used, and the marker still upholds the
        # deletion-protocol barrier.
        payload = np.asarray(arr) if self.rank == root_rank else 0
        return self._round(payload, lambda parts: parts[root_rank], timeout_ms)

    def allgather(self, arr, timeout_ms: int) -> List[np.ndarray]:
        return self._round(np.asarray(arr), lambda parts: list(parts), timeout_ms)

    def reducescatter(self, arr, op: ReduceOp, timeout_ms: int) -> np.ndarray:
        full = self.allreduce(arr, op, timeout_ms)
        return np.array_split(full, self.world_size, axis=0)[self.rank]

    def barrier(self, timeout_ms: int) -> None:
        self._round(0, lambda parts: 0, timeout_ms)

    def send(self, arr, dst_rank: int, timeout_ms: int) -> None:
        key = (self.rank, dst_rank)
        seq = self._p2p_seq.get(key, 0)
        self._p2p_seq[key] = seq + 1
        _kv().kv_put(
            f"{self.group_name}:p2p:{self.rank}>{dst_rank}:{seq}",
            np.asarray(arr),
            ns=_KV_NS,
        )

    def recv(self, src_rank: int, timeout_ms: int) -> np.ndarray:
        key = (src_rank, self.rank)
        seq = self._p2p_seq.get(key, 0)
        self._p2p_seq[key] = seq + 1
        return self._poll(
            f"{self.group_name}:p2p:{src_rank}>{self.rank}:{seq}",
            timeout_ms,
            delete=True,
        )

    def destroy(self) -> None:
        kv = _kv()
        try:
            for k in kv.kv_keys(self.group_name + ":", ns=_KV_NS):
                kv.kv_del(k, ns=_KV_NS)
        except Exception:  # controller may already be gone at shutdown
            pass


class XlaGroup(BaseGroup):
    """Device-plane collectives: jitted XLA programs over a one-axis mesh.

    Each rank is one *process* of a shared `jax.distributed` runtime (for
    world_size == 1, plain local JAX). The mesh takes one device per process;
    a collective builds a global array with each process's contribution as its
    addressable shard and jits the reduction with a replicated out-sharding,
    so XLA emits the all-reduce/all-gather over ICI/DCN.
    """

    def __init__(
        self,
        world_size: int,
        rank: int,
        group_name: str,
        *,
        coordinator: Optional[str] = None,
    ):
        super().__init__(world_size, rank, group_name)
        import jax

        # The is_initialized() check must come before ANY backend-touching JAX
        # call (process_count() would initialize XLA and make
        # distributed.initialize() unconstructible).
        if world_size > 1 and not jax.distributed.is_initialized():
            # Join (or start) the shared distributed runtime. Rank 0 publishes
            # the coordinator endpoint through the controller KV.
            coord_key = f"{group_name}:coordinator"
            if coordinator is None:
                if rank == 0:
                    import socket

                    sock = socket.socket()
                    sock.bind(("", 0))
                    port = sock.getsockname()[1]
                    sock.close()
                    coordinator = f"{_node_ip()}:{port}"
                    _kv().kv_put(coord_key, coordinator, ns=_KV_NS)
                else:
                    deadline = time.monotonic() + 30
                    while coordinator is None:
                        coordinator = _kv().kv_get(coord_key, ns=_KV_NS)
                        if coordinator is None:
                            if time.monotonic() > deadline:
                                raise TimeoutError("no coordinator published")
                            time.sleep(0.05)
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=world_size,
                process_id=rank,
            )
        if jax.process_count() != world_size:
            raise RuntimeError(
                f"xla backend: jax.process_count()={jax.process_count()} but "
                f"world_size={world_size}; start one process per rank"
            )
        if world_size > 1 and jax.process_index() != rank:
            # The mesh below places each process's shard at its
            # process_index; a pre-initialized runtime whose rank assignment
            # differs would silently reorder broadcast/allgather results.
            raise RuntimeError(
                f"xla backend: jax.process_index()={jax.process_index()} "
                f"must equal the collective rank ({rank})"
            )
        self._jax = jax
        # one device per process, ordered by rank
        per_proc: Dict[int, Any] = {}
        for d in jax.devices():
            per_proc.setdefault(d.process_index, d)
        devs = [per_proc[i] for i in range(world_size)]
        from jax.sharding import Mesh

        self._mesh = Mesh(np.array(devs), ("ranks",))
        self._local_device = per_proc[jax.process_index()]
        # KV side-channel for p2p
        self._host = HostGroup(world_size, rank, group_name + ":p2p") if world_size > 1 else None
        # One jitted program per op kind, reused across calls (jax.jit caches
        # by function identity — fresh lambdas per call would recompile).
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        replicated = NamedSharding(self._mesh, P())
        self._programs = {
            ReduceOp.SUM: jax.jit(lambda a: jnp.sum(a, axis=0), out_shardings=replicated),
            ReduceOp.MEAN: jax.jit(lambda a: jnp.mean(a, axis=0), out_shardings=replicated),
            ReduceOp.PRODUCT: jax.jit(lambda a: jnp.prod(a, axis=0), out_shardings=replicated),
            ReduceOp.MAX: jax.jit(lambda a: jnp.max(a, axis=0), out_shardings=replicated),
            ReduceOp.MIN: jax.jit(lambda a: jnp.min(a, axis=0), out_shardings=replicated),
            "identity": jax.jit(lambda a: a, out_shardings=replicated),
            "take": jax.jit(
                lambda a, i: jax.lax.dynamic_index_in_dim(a, i, keepdims=False),
                out_shardings=replicated,
            ),
        }

    def _global(self, x: np.ndarray):
        jax = self._jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = np.asarray(x)
        shard = jax.device_put(x[None], self._local_device)
        return jax.make_array_from_single_device_arrays(
            (self.world_size,) + x.shape,
            NamedSharding(self._mesh, P("ranks")),
            [shard],
        )

    def allreduce(self, arr, op: ReduceOp, timeout_ms: int) -> np.ndarray:
        out = self._programs[op](self._global(arr))
        return np.asarray(out.addressable_data(0))

    def reduce(self, arr, op: ReduceOp, root_rank: int, timeout_ms: int):
        out = self.allreduce(arr, op, timeout_ms)
        return out if self.rank == root_rank else np.asarray(arr)

    def broadcast(self, arr, root_rank: int, timeout_ms: int):
        out = self._programs["take"](self._global(arr), root_rank)
        return np.asarray(out.addressable_data(0))

    def allgather(self, arr, timeout_ms: int) -> List[np.ndarray]:
        out = self._programs["identity"](self._global(arr))
        return list(np.asarray(out.addressable_data(0)))

    def reducescatter(self, arr, op: ReduceOp, timeout_ms: int) -> np.ndarray:
        full = self.allreduce(arr, op, timeout_ms)
        return np.array_split(full, self.world_size, axis=0)[self.rank]

    def barrier(self, timeout_ms: int) -> None:
        self.allreduce(np.zeros((1,), np.float32), ReduceOp.SUM, timeout_ms)

    def send(self, arr, dst_rank: int, timeout_ms: int) -> None:
        if self._host is None:
            raise RuntimeError("send/recv needs world_size > 1")
        self._host.send(arr, dst_rank, timeout_ms)

    def recv(self, src_rank: int, timeout_ms: int) -> np.ndarray:
        if self._host is None:
            raise RuntimeError("send/recv needs world_size > 1")
        return self._host.recv(src_rank, timeout_ms)

    def destroy(self) -> None:
        if self._host is not None:
            self._host.destroy()
        try:
            _kv().kv_del(f"{self.group_name}:coordinator", ns=_KV_NS)
        except Exception:
            pass


_BACKENDS = {Backend.HOST: HostGroup, Backend.XLA: XlaGroup}


# ------------------------------------------------------------- group manager


class GroupManager:
    def __init__(self):
        self._groups: Dict[str, BaseGroup] = {}
        self._lock = threading.Lock()

    def create(
        self,
        backend: Backend,
        world_size: int,
        rank: int,
        name: str,
        *,
        public_name: Optional[str] = None,
    ) -> BaseGroup:
        """`name` keys the wire protocol (KV keys); `public_name` (default:
        same) keys the local registry callers look groups up by."""
        with self._lock:
            key = public_name or name
            if key in self._groups:
                raise RuntimeError(f"collective group {key!r} already initialized")
            group = _BACKENDS[backend](world_size, rank, name)
            self._groups[key] = group
            return group

    def get(self, name: str) -> Optional[BaseGroup]:
        with self._lock:
            return self._groups.get(name)

    def destroy(self, name: str) -> None:
        with self._lock:
            group = self._groups.pop(name, None)
        if group is not None:
            group.destroy()


_manager = GroupManager()


def _resolve_group(group_name: str) -> BaseGroup:
    group = _manager.get(group_name)
    if group is not None:
        if getattr(group, "_decl_gen", None) is not None:
            # Declaratively-created: guard against the driver having destroyed
            # and re-created a same-named group with different membership.
            meta = _kv().kv_get(f"decl:{group_name}", ns=_KV_NS)
            if meta is None or meta["gen"] != group._decl_gen:
                _manager.destroy(group_name)
                group = None
        if group is not None:
            return group
    # Declarative path (≈ collective.py:151): the driver stored group metadata
    # in the controller KV keyed by group name; resolve our rank by actor id.
    meta = _kv().kv_get(f"decl:{group_name}", ns=_KV_NS)
    if meta is None:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized in this "
            "process; call init_collective_group or create_collective_group"
        )
    from ray_tpu._private.api import get_runtime_context

    my_actor = get_runtime_context().actor_id
    if my_actor is None or my_actor not in meta["actor_ids"]:
        raise RuntimeError(
            f"this process is not a member of collective group {group_name!r}"
        )
    rank = meta["ranks"][meta["actor_ids"].index(my_actor)]
    group = _manager.create(
        Backend.parse(meta["backend"]),
        meta["world_size"],
        rank,
        # Key the wire protocol by generation so a stale member erring out is
        # a timeout, never a silent cross-generation mix.
        f"{group_name}@{meta['gen']}",
        public_name=group_name,
    )
    group._decl_gen = meta["gen"]
    return group


# ------------------------------------------------------------- public API


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "host",
    group_name: str = "default",
) -> None:
    """Imperative init, called inside each participating task/actor
    (≈ collective.py:120)."""
    _manager.create(Backend.parse(backend), world_size, rank, group_name)


def create_collective_group(
    actors: Sequence[Any],
    world_size: int,
    ranks: Sequence[int],
    backend: str = "host",
    group_name: str = "default",
) -> None:
    """Declarative init from the driver over actor handles
    (≈ collective.py:151): stores membership in the controller KV; each actor
    resolves its rank lazily on its first collective call."""
    if len(actors) != len(ranks) or len(actors) != world_size:
        raise ValueError("need exactly world_size actors and ranks")
    if sorted(ranks) != list(range(world_size)):
        raise ValueError(f"ranks must be a permutation of 0..{world_size - 1}")
    actor_ids = [a._actor_id.hex() for a in actors]
    # The generation counter lives under its own key that destroy_* never
    # deletes: re-creating a destroyed group must still advance the gen, or
    # stale members (and their leftover wire keys from the old generation)
    # would silently mix into the new group.
    prev_gen = _kv().kv_get(f"declgen:{group_name}", ns=_KV_NS)
    gen = (prev_gen + 1) if prev_gen is not None else 0
    _kv().kv_put(f"declgen:{group_name}", gen, ns=_KV_NS)
    _kv().kv_put(
        f"decl:{group_name}",
        {
            "world_size": world_size,
            "ranks": list(ranks),
            "backend": str(Backend.parse(backend).value),
            "actor_ids": actor_ids,
            "gen": gen,
        },
        ns=_KV_NS,
    )


def is_group_initialized(group_name: str = "default") -> bool:
    return _manager.get(group_name) is not None


def destroy_collective_group(group_name: str = "default") -> None:
    _manager.destroy(group_name)
    try:
        _kv().kv_del(f"decl:{group_name}", ns=_KV_NS)
    except Exception:
        pass


def get_rank(group_name: str = "default") -> int:
    return _resolve_group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _resolve_group(group_name).world_size


DEFAULT_TIMEOUT_MS = 30000


def allreduce(
    tensor,
    group_name: str = "default",
    op: ReduceOp = ReduceOp.SUM,
    timeout_ms: int = DEFAULT_TIMEOUT_MS,
):
    """Allreduce across the group (returns the reduced array; ≈ collective.py:258)."""
    return _resolve_group(group_name).allreduce(tensor, op, timeout_ms)


def reduce(
    tensor,
    dst_rank: int = 0,
    group_name: str = "default",
    op: ReduceOp = ReduceOp.SUM,
    timeout_ms: int = DEFAULT_TIMEOUT_MS,
):
    return _resolve_group(group_name).reduce(tensor, op, dst_rank, timeout_ms)


def broadcast(
    tensor,
    src_rank: int = 0,
    group_name: str = "default",
    timeout_ms: int = DEFAULT_TIMEOUT_MS,
):
    return _resolve_group(group_name).broadcast(tensor, src_rank, timeout_ms)


def allgather(
    tensor, group_name: str = "default", timeout_ms: int = DEFAULT_TIMEOUT_MS
) -> List[np.ndarray]:
    return _resolve_group(group_name).allgather(tensor, timeout_ms)


def reducescatter(
    tensor,
    group_name: str = "default",
    op: ReduceOp = ReduceOp.SUM,
    timeout_ms: int = DEFAULT_TIMEOUT_MS,
):
    return _resolve_group(group_name).reducescatter(tensor, op, timeout_ms)


def send(
    tensor, dst_rank: int, group_name: str = "default", timeout_ms: int = DEFAULT_TIMEOUT_MS
) -> None:
    _resolve_group(group_name).send(tensor, dst_rank, timeout_ms)


def recv(
    src_rank: int, group_name: str = "default", timeout_ms: int = DEFAULT_TIMEOUT_MS
) -> np.ndarray:
    """Receive from src_rank. (The reference mutates a passed-in tensor; we
    return the received array — functional style, consistent with JAX.)"""
    return _resolve_group(group_name).recv(src_rank, timeout_ms)


def barrier(group_name: str = "default", timeout_ms: int = DEFAULT_TIMEOUT_MS) -> None:
    _resolve_group(group_name).barrier(timeout_ms)


def synchronize(group_name: str = "default") -> None:
    """Block until all queued device work is done (≈ cuda synchronize)."""
    try:
        import jax

        (jax.device_put(0.0) + 0).block_until_ready()
    except Exception:
        pass
