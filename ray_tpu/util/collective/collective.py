"""Collective communication API between tasks/actors.

API shaped like the reference's `ray.util.collective.collective`
(`python/ray/util/collective/collective.py:120-655`: init_collective_group,
create_collective_group, allreduce :258, reduce :311, broadcast :373,
allgather :423, reducescatter :472, send :531, recv :594, barrier), re-based
for TPU:

  * backend "xla" ≈ the reference's NCCL group — but instead of explicit
    device-to-device NCCL calls, ranks join one `jax.distributed` runtime and
    every collective is a jitted XLA program over a one-axis device mesh, so
    the bytes ride ICI/DCN exactly as GSPMD would move them.
  * backend "host" ≈ the reference's Gloo group — the controller is used for
    **group rendezvous only**; tensor bytes move peer-to-peer. Same-node
    groups reduce through pin-backed shared-memory channels
    (`collective/shm.py` — a steady-state allreduce issues ZERO control-plane
    RPCs), cross-node groups run ring reduce-scatter + allgather over direct
    worker↔worker chunked RPCs (`collective/ring.py` — O(N) per link instead
    of O(N·world) through one controller socket, tensors larger than the RPC
    MAX_FRAME stream as bounded-window frames). The legacy controller-KV
    rounds survive as the explicit ``algo="kv"`` baseline.

Both imperative (`init_collective_group` inside each worker) and declarative
(`create_collective_group` from the driver over actor handles) setup are
supported, mirroring collective.py:120/:151.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.util.collective.types import (Backend, CollectiveError,
                                           ReduceOp, check_inplace_out)

logger = logging.getLogger(__name__)

_KV_NS = "collective"


def _kv():
    from ray_tpu._private import internal_kv

    return internal_kv


def _sweep_group_keys(group_name: str) -> None:
    """Best-effort delete of every wire key under ``{group_name}:`` (group
    teardown; the controller may already be gone at shutdown)."""
    kv = _kv()
    try:
        for k in kv.kv_keys(group_name + ":", ns=_KV_NS):
            kv.kv_del(k, ns=_KV_NS)
    except Exception:
        pass


def _node_ip() -> str:
    """Best reachable address of this host for cross-host rendezvous."""
    import socket

    ip = os.environ.get("RAY_TPU_NODE_IP")
    if ip:
        return ip
    try:
        # UDP connect picks the outbound interface without sending a packet.
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            ip = s.getsockname()[0]
        finally:
            s.close()
        if ip and not ip.startswith("127."):
            return ip
    except OSError:
        pass
    try:
        ip = socket.gethostbyname(socket.gethostname())
        if ip and not ip.startswith("127."):
            return ip
    except OSError:
        pass
    return "127.0.0.1"


def _default_bucket_bytes() -> int:
    try:
        from ray_tpu._private.api import _require_core

        return _require_core().config.collective_coalesce_bytes
    except Exception:
        return 32 * 1024**2


def _overlap_enabled() -> bool:
    """The RAY_TPU_COLLECTIVE_OVERLAP knob (config-backed; default on)."""
    try:
        from ray_tpu._private.api import _require_core

        return bool(_require_core().config.collective_overlap)
    except Exception:
        from ray_tpu._private.config import global_config

        return bool(global_config().collective_overlap)


# --------------------------------------------------------------------- groups


class BaseGroup:
    def __init__(self, world_size: int, rank: int, group_name: str):
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} out of range for world_size {world_size}")
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self._public_name = group_name
        self._decl_gen = None  # set when created from declarative KV metadata

    def destroy(self) -> None:
        pass

    def allreduce_coalesced(
        self,
        tensors: Sequence[Any],
        op: ReduceOp,
        timeout_ms: int,
        bucket_bytes: Optional[int] = None,
        out: Optional[Sequence[Any]] = None,
    ) -> List[np.ndarray]:
        """Allreduce a LIST of tensors in same-dtype buckets: adjacent
        tensors pack into one flat vector per bucket (bounded by
        ``collective_coalesce_bytes``), so a gradient tree costs one
        collective round per bucket — not one per leaf, and not one
        monolithic ``np.concatenate`` copy of the whole tree either.
        The bucket reduces IN PLACE over its staging vector, a MEAN
        pre-scales into the pack copy (no post-reduce divide pass), and
        ``out=`` (persistent arrays, input shapes/dtypes) lands results
        without allocating. Returns reduced arrays with the input
        shapes, in input order."""
        from ray_tpu.util.collective.async_work import (bucket_layout,
                                                        validate_out)
        from ray_tpu.util.collective.types import prescale_factor

        arrs = [np.asarray(t) for t in tensors]
        if not arrs:
            return []
        validate_out(arrs, op, out, self.world_size)
        if bucket_bytes is None:
            bucket_bytes = _default_bucket_bytes()
        results: List[Optional[np.ndarray]] = [None] * len(arrs)
        for bucket in bucket_layout(arrs, bucket_bytes):
            dtype = arrs[bucket[0]].dtype
            total = sum(arrs[i].size for i in bucket)
            vec = np.empty(total, dtype)
            scale = prescale_factor(op, dtype, self.world_size)
            off = 0
            for i in bucket:
                flat = np.ascontiguousarray(arrs[i]).reshape(-1)
                seg = vec[off:off + arrs[i].size]
                if scale is None:
                    seg[...] = flat
                else:
                    np.multiply(flat, scale, out=seg)
                off += arrs[i].size
            round_op = ReduceOp.SUM if op is ReduceOp.MEAN else op
            red = np.asarray(
                self.allreduce(vec, round_op, timeout_ms, out=vec))
            if op is ReduceOp.MEAN and scale is None:
                red = red / self.world_size  # integer mean fallback
            off = 0
            for i in bucket:
                seg = red[off:off + arrs[i].size].reshape(arrs[i].shape)
                if out is not None:
                    np.copyto(out[i], seg)
                    results[i] = out[i]
                else:
                    results[i] = seg
                off += arrs[i].size
        return results  # type: ignore[return-value]

    def allreduce_coalesced_async(
        self,
        tensors: Sequence[Any],
        op: ReduceOp,
        timeout_ms: int,
        bucket_bytes: Optional[int] = None,
        out: Optional[Sequence[Any]] = None,
        overlap: Optional[bool] = None,
        on_bucket=None,
    ):
        """Async-handle form of :meth:`allreduce_coalesced`. The base
        implementation (xla backend, and the explicit
        ``RAY_TPU_COLLECTIVE_OVERLAP=0`` fallback on the host backend)
        runs synchronously and returns an already-completed handle —
        callers write one code path and the knob decides. ``on_bucket``
        (when given) still fires exactly once per bucket, on the
        caller's thread, in the runner's reverse-flatten order — the
        per-bucket contract holds on every path, only the overlap is
        lost."""
        from ray_tpu.util.collective.async_work import (_CompletedWork,
                                                        fire_on_bucket,
                                                        validate_on_bucket)

        validate_on_bucket(on_bucket)
        results = self.allreduce_coalesced(tensors, op, timeout_ms,
                                           bucket_bytes, out=out)
        if on_bucket is not None and len(results):
            leaves = [t if hasattr(t, "dtype") and hasattr(t, "size")
                      else np.asarray(t) for t in tensors]
            fire_on_bucket(
                leaves,
                bucket_bytes if bucket_bytes is not None
                else _default_bucket_bytes(),
                results, on_bucket)
        return _CompletedWork(self._public_name, results)

    def _raise_if_stale(self) -> None:
        """After a timeout/peer failure on a declaratively-created group,
        distinguish 'the driver destroyed/re-created this group' from a
        plain peer problem. This runs ONLY on the failure path — the
        steady state never re-validates membership, so a healthy
        collective issues no control-plane RPCs for it."""
        if self._decl_gen is None:
            return
        meta = _kv().kv_get(f"decl:{self._public_name}", ns=_KV_NS)
        if meta is None or meta["gen"] != self._decl_gen:
            _manager.destroy(self._public_name)
            raise RuntimeError(
                f"collective group {self._public_name!r} was destroyed or "
                f"re-created by the driver while this rank was using it; "
                f"retry the collective to join the new generation")


def _reduce_fn(op: ReduceOp):
    return {
        ReduceOp.SUM: lambda a: a.sum(axis=0),
        ReduceOp.MEAN: lambda a: a.mean(axis=0),
        ReduceOp.PRODUCT: lambda a: a.prod(axis=0),
        ReduceOp.MAX: lambda a: a.max(axis=0),
        ReduceOp.MIN: lambda a: a.min(axis=0),
    }[op]


class _SoloGroup:
    """world_size == 1: every collective is the identity, locally."""

    algo = "solo"

    def allreduce(self, arr, op, timeout_ms, out=None):
        if out is not None:
            src = np.asarray(arr)
            check_inplace_out(out, src)
            if out is not src:
                np.copyto(out.reshape(src.shape), src)
            return out
        return np.array(arr, copy=True)

    def reduce(self, arr, op, root_rank, timeout_ms):
        return np.array(arr, copy=True)

    def broadcast(self, arr, root_rank, timeout_ms):
        return np.asarray(arr)

    def allgather(self, arr, timeout_ms):
        return [np.asarray(arr)]

    def reducescatter(self, arr, op, timeout_ms):
        return np.array_split(np.asarray(arr), 1, axis=0)[0]

    def barrier(self, timeout_ms):
        pass

    def send(self, arr, dst_rank, timeout_ms):
        raise RuntimeError("send/recv needs world_size > 1")

    def recv(self, src_rank, timeout_ms):
        raise RuntimeError("send/recv needs world_size > 1")

    def destroy(self):
        pass


class KvGroup:
    """Legacy control-plane collectives over the controller KV.

    Kept as the explicit ``algo="kv"`` baseline (the `collective_speedup`
    microbench probe compares the p2p data plane against it) and as the
    fallback when no peer data plane is possible. Every rank's full
    tensor transits the controller — O(N·world) through one socket; the
    payload cap (`RAY_TPU_KV_MAX_VALUE_BYTES`) bounds the damage.

    Protocol: every collective call gets a per-group sequence number (all
    ranks call collectives in the same order — the standard requirement).
    Ranks post contributions under ``{group}:{seq}:c:{rank}``; rank 0
    reduces and posts ``{group}:{seq}:r``; ranks poll for the result.
    Rank 0 deletes the previous call's result right before posting the
    next one — safe, because holding every contribution of call N implies
    every rank has read the result of call N-1. The FINAL round's result
    key is reaped by a deferred sweep (one timer per group) once the
    call's timeout window has passed, so a long-lived idle group leaks
    nothing even without ``destroy()``.
    """

    algo = "kv"

    def __init__(self, world_size: int, rank: int, group_name: str):
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self._seq = 0
        self._p2p_seq: Dict[tuple, int] = {}
        self._sweeper: Optional[threading.Timer] = None
        self._destroyed = False

    # ----- kv plumbing

    def _key(self, seq: int, kind: str, rank: Optional[int] = None) -> str:
        k = f"{self.group_name}:{seq}:{kind}"
        return k if rank is None else f"{k}:{rank}"

    def _poll(self, key: str, timeout_ms: int, delete: bool = False):
        kv = _kv()
        deadline = time.monotonic() + timeout_ms / 1000.0
        pause = 0.001
        while True:
            val = kv.kv_get(key, ns=_KV_NS)
            if val is not None:
                if delete:
                    kv.kv_del(key, ns=_KV_NS)
                return val
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"collective group {self.group_name!r} rank {self.rank}: "
                    f"timed out waiting for {key!r}"
                )
            time.sleep(pause)
            pause = min(pause * 1.5, 0.05)

    def _schedule_sweep(self, seq: int, timeout_ms: int) -> None:
        """Rank 0 only: reap ``{seq}:r`` after the call's timeout window
        if no newer round superseded it (at that point every other rank
        has either read the result or timed out — deleting is safe)."""
        if self._sweeper is not None:
            self._sweeper.cancel()

        def sweep() -> None:
            if self._destroyed or self._seq != seq + 1:
                return
            try:
                _kv().kv_del(self._key(seq, "r"), ns=_KV_NS)
            except Exception:
                pass  # controller may be gone at shutdown

        t = threading.Timer(max(1.0, timeout_ms / 1000.0), sweep)
        t.daemon = True
        t.start()
        self._sweeper = t

    def _round(self, payload, combine, timeout_ms: int):
        """One gather-to-root + broadcast round; returns the combined result."""
        kv = _kv()
        seq, self._seq = self._seq, self._seq + 1
        if self.rank == 0:
            # rank 0 is the reducer: its own contribution never needs to
            # transit the controller — use the local payload in place.
            parts = [
                payload if r == 0 else
                self._poll(self._key(seq, "c", r), timeout_ms, delete=True)
                for r in range(self.world_size)
            ]
            result = combine(parts)
            if seq > 0:
                kv.kv_del(self._key(seq - 1, "r"), ns=_KV_NS)
            kv.kv_put(self._key(seq, "r"), result, ns=_KV_NS)
            self._schedule_sweep(seq, timeout_ms)
            return result
        kv.kv_put(self._key(seq, "c", self.rank), payload, ns=_KV_NS)
        return self._poll(self._key(seq, "r"), timeout_ms)

    # ----- ops

    def allreduce(self, arr: np.ndarray, op: ReduceOp, timeout_ms: int,
                  out: Optional[np.ndarray] = None) -> np.ndarray:
        from ray_tpu.util.collective import _metrics

        fn = _reduce_fn(op)
        with _metrics.round_timer(self.algo):
            red = self._round(
                np.asarray(arr), lambda parts: fn(np.stack(parts)), timeout_ms
            )
        _metrics.ops_total.inc(labels=_metrics.labels(self.algo))
        _metrics.bytes_total.inc(np.asarray(arr).nbytes,
                                 labels=_metrics.labels(self.algo))
        if out is not None:
            red = np.asarray(red)
            check_inplace_out(out, red)
            np.copyto(out.reshape(red.shape), red)
            return out
        return red

    def reduce(self, arr, op: ReduceOp, root_rank: int, timeout_ms: int):
        out = self.allreduce(arr, op, timeout_ms)
        return out if self.rank == root_rank else np.asarray(arr)

    def broadcast(self, arr, root_rank: int, timeout_ms: int):
        # Non-root ranks post a tiny marker instead of their full tensor: only
        # root's contribution is used, and the marker still upholds the
        # deletion-protocol barrier.
        payload = np.asarray(arr) if self.rank == root_rank else 0
        return self._round(payload, lambda parts: parts[root_rank], timeout_ms)

    def allgather(self, arr, timeout_ms: int) -> List[np.ndarray]:
        return self._round(np.asarray(arr), lambda parts: list(parts), timeout_ms)

    def reducescatter(self, arr, op: ReduceOp, timeout_ms: int) -> np.ndarray:
        full = self.allreduce(arr, op, timeout_ms)
        return np.array_split(full, self.world_size, axis=0)[self.rank]

    def barrier(self, timeout_ms: int) -> None:
        self._round(0, lambda parts: 0, timeout_ms)

    def send(self, arr, dst_rank: int, timeout_ms: int) -> None:
        key = (self.rank, dst_rank)
        seq = self._p2p_seq.get(key, 0)
        self._p2p_seq[key] = seq + 1
        _kv().kv_put(
            f"{self.group_name}:p2p:{self.rank}>{dst_rank}:{seq}",
            np.asarray(arr),
            ns=_KV_NS,
        )

    def recv(self, src_rank: int, timeout_ms: int) -> np.ndarray:
        key = (src_rank, self.rank)
        seq = self._p2p_seq.get(key, 0)
        self._p2p_seq[key] = seq + 1
        return self._poll(
            f"{self.group_name}:p2p:{src_rank}>{self.rank}:{seq}",
            timeout_ms,
            delete=True,
        )

    def destroy(self) -> None:
        self._destroyed = True
        if self._sweeper is not None:
            self._sweeper.cancel()
        _sweep_group_keys(self.group_name)


class HostGroup(BaseGroup):
    """Host-backend facade: one controller-KV rendezvous, then a
    peer-to-peer data plane.

    The data-path algorithm resolves lazily on the first collective call
    (by then every rank has initialized, so the rendezvous completes):

      * ``shm``  — every rank on one node: pin-backed shared-memory
        channel rounds, zero steady-state control-plane RPCs;
      * ``ring`` — ranks span nodes: ring reduce-scatter + allgather over
        chunked direct worker↔worker RPCs;
      * ``kv``   — the legacy controller-KV rounds (explicit opt-in /
        comparison baseline);
      * ``auto`` (default) — shm when possible, else ring.

    Force one via ``RAY_TPU_COLLECTIVE_ALGO`` or the ``algo=`` argument
    of ``init_collective_group``.
    """

    def __init__(self, world_size: int, rank: int, group_name: str,
                 *, algo: Optional[str] = None):
        super().__init__(world_size, rank, group_name)
        self._algo_override = algo
        self._impl = None
        self._impl_lock = threading.Lock()
        self._poisoned: Optional[str] = None
        self._runner = None  # async overlap runner, built on first use
        # publish this rank's rendezvous record EAGERLY (best-effort): a
        # peer's send/recv must be able to reach a rank that initialized
        # the group but has not yet issued a collective of its own —
        # otherwise pairwise p2p would hang waiting on bystander ranks
        if world_size > 1 and (algo or "").lower() != "kv":
            try:
                self._publish_rendezvous()
            except Exception:
                logger.debug("eager collective rendezvous publish failed "
                             "(will retry on first use)", exc_info=True)

    def _publish_rendezvous(self) -> dict:
        from ray_tpu._private.api import _require_core
        from ray_tpu.util.collective import ring as _ring_mod

        core = _require_core()
        # handlers register BEFORE the address goes public, so no peer
        # frame can ever arrive unroutable
        _ring_mod.ensure_registered(core)
        me = {"addr": list(core.address), "node": core.node_id_hex,
              "client": core._store_client_id}
        if self.rank == 0:
            # Rank 0 mints a per-incarnation token that every rank folds
            # into the transport wire name: a destroy + re-init of the SAME
            # imperative group name gets fresh inbox keys and fresh shm
            # channel-spec KV keys, so a chaos-delayed duplicate frame (or
            # a stale channel record) from the previous incarnation can
            # never be mistaken for this one's data. (Declarative groups
            # already get this from their gen-suffixed wire name.)
            if not hasattr(self, "_rv_token"):
                self._rv_token = os.urandom(8).hex()
            me["token"] = self._rv_token
        _kv().kv_put(f"{self.group_name}:rv:{self.rank}", me, ns=_KV_NS)
        return me

    @property
    def algo(self) -> str:
        """Resolved data-path algorithm ('' until the first collective)."""
        return self._impl.algo if self._impl is not None else ""

    def _impl_for(self, timeout_ms: int):
        if self._impl is not None:
            return self._impl
        with self._impl_lock:
            if self._impl is None:
                self._impl = self._resolve_impl(timeout_ms)
        return self._impl

    def _resolve_impl(self, timeout_ms: int):
        from ray_tpu._private.api import _require_core
        from ray_tpu.util.collective import ring as _ring_mod
        from ray_tpu.util.collective import shm as _shm_mod

        if self.world_size == 1:
            return _SoloGroup()
        core = _require_core()
        algo = (self._algo_override or core.config.collective_algo
                or "auto").lower()
        if algo == "kv":
            return KvGroup(self.world_size, self.rank, self.group_name)
        if algo not in ("auto", "shm", "ring"):
            raise ValueError(
                f"unknown collective algo {algo!r}; use auto/shm/ring/kv")
        # rendezvous: (re-)publish this rank's worker RPC address + node
        # identity; the controller carries these few hundred bytes and
        # never a tensor.
        me = self._publish_rendezvous()
        deadline = time.monotonic() + max(1.0, timeout_ms / 1000.0)
        peers: Dict[int, dict] = {self.rank: me}
        for p in range(self.world_size):
            if p == self.rank:
                continue
            peers[p] = _kv().kv_wait(
                f"{self.group_name}:rv:{p}",
                timeout=max(0.1, deadline - time.monotonic()),
                ns=_KV_NS)
        same_node = bool(core.node_id_hex) and all(
            peers[p]["node"] == core.node_id_hex for p in peers)
        # rank 0's incarnation token keys the data plane (see
        # _publish_rendezvous); every rank read the same rv:0 record, so
        # every rank derives the same wire name
        wire = f"{self.group_name}#{peers[0].get('token', '')}"
        if algo == "auto":
            from ray_tpu._private.channels import MAX_READERS

            shm_ok = (same_node and core.arena is not None
                      and self.world_size - 1 <= MAX_READERS)
            algo = "shm" if shm_ok else "ring"
        if algo == "shm":
            if not same_node:
                raise CollectiveError(
                    f"collective group {self.group_name!r}: algo 'shm' "
                    f"forced but ranks span nodes — use 'ring' or 'auto'")
            if core.arena is None:
                raise CollectiveError(
                    f"collective group {self.group_name!r}: algo 'shm' "
                    f"forced but this process has no node arena mapping — "
                    f"use 'ring' or 'auto'")
            # no silent ring fallback on a setup failure: the algo choice
            # above is a pure function of the rendezvous records, so every
            # rank picks the same one — a per-rank fallback would leave
            # this rank ringing while its peers sit on channels (mutual
            # timeout at best, and the failure deserves to be loud anyway)
            return _shm_mod.ShmGroup(
                core, self.world_size, self.rank, wire, peers, timeout_ms)
        return _ring_mod.RingGroup(
            core, self.world_size, self.rank, wire, peers)

    # ----- async overlap runner

    def _ensure_runner(self):
        from ray_tpu.util.collective.async_work import AsyncRunner

        if self._runner is not None:
            # fast path OUTSIDE the lock: the reducer thread holds
            # _impl_lock for the whole first rendezvous — a submit during
            # that round must still return immediately
            return self._runner
        with self._impl_lock:
            if self._runner is None:
                self._runner = AsyncRunner(self)
        return self._runner

    def allreduce_coalesced_async(
        self,
        tensors: Sequence[Any],
        op: ReduceOp,
        timeout_ms: int,
        bucket_bytes: Optional[int] = None,
        out: Optional[Sequence[Any]] = None,
        overlap: Optional[bool] = None,
        on_bucket=None,
    ):
        """Overlapped coalesced allreduce: returns a ``CollectiveWork``
        immediately; the group's runner pipelines per-bucket device->host
        transfers against shm/ring reduce rounds. ``on_bucket(indices,
        arrays)`` fires on the runner's reducer thread the moment each
        bucket's reduce lands — per-bucket downstream work (e.g. a
        fused optimizer apply) overlaps the remaining buckets' rounds.
        ``overlap=False`` (or ``RAY_TPU_COLLECTIVE_OVERLAP=0``) takes
        the synchronous path and returns an already-completed handle."""
        if overlap is None:
            overlap = _overlap_enabled()
        if not overlap or self.world_size == 1:
            return super().allreduce_coalesced_async(
                tensors, op, timeout_ms, bucket_bytes, out=out,
                on_bucket=on_bucket)
        from ray_tpu.util.collective.async_work import validate_on_bucket

        validate_on_bucket(on_bucket)
        if self._poisoned is not None:
            # same staleness-first remedy as the sync path: a driver
            # re-create of this declarative group drops the cached member
            self._raise_if_stale()
            raise CollectiveError(
                f"collective group {self._public_name!r} is poisoned by an "
                f"earlier failure ({self._poisoned}); destroy and re-create "
                f"the group")
        if bucket_bytes is None:
            bucket_bytes = _default_bucket_bytes()
        return self._ensure_runner().submit(
            tensors, op, timeout_ms, bucket_bytes, out, on_bucket=on_bucket)

    # ----- delegated ops (stale-generation check on the failure path)

    def _delegate(self, timeout_ms: int, fn):
        if self._runner is not None:
            # sync ops order AFTER in-flight async work on every rank —
            # the transport must see one identical op sequence everywhere
            self._runner.flush(max(1.0, timeout_ms / 1000.0))
        if self._poisoned is not None:
            # staleness first: if the driver already destroyed and
            # re-created this declarative group (the documented remedy for
            # a poisoned group), _raise_if_stale drops the cached member so
            # the next call joins the new generation instead of raising
            # 'poisoned' forever
            self._raise_if_stale()
            raise CollectiveError(
                f"collective group {self._public_name!r} is poisoned by an "
                f"earlier failure ({self._poisoned}); destroy and re-create "
                f"the group")
        impl = self._impl_for(timeout_ms)
        try:
            return fn(impl)
        except (TimeoutError, CollectiveError) as e:
            # A mid-collective failure can leave per-pair sequence counters
            # (ring inbox) or seqlock versions (shm channels) out of step
            # with what peers actually committed; a RETRIED collective could
            # then consume a stale round as fresh data. Poison the group so
            # every later call fails clean — never a silently wrong sum.
            self._poisoned = f"{type(e).__name__}: {e}"
            self._raise_if_stale()
            raise
        except Exception as e:  # noqa: BLE001 — e.g. a shape ValueError
            # ANY exception escaping mid-op may have advanced transport
            # state already (segments sent, versions bumped) — same poison,
            # same reason
            self._poisoned = f"{type(e).__name__}: {e}"
            raise

    def allreduce(self, arr, op: ReduceOp, timeout_ms: int,
                  out: Optional[np.ndarray] = None) -> np.ndarray:
        return self._delegate(
            timeout_ms, lambda g: g.allreduce(arr, op, timeout_ms, out=out))

    def reduce(self, arr, op: ReduceOp, root_rank: int, timeout_ms: int):
        return self._delegate(
            timeout_ms, lambda g: g.reduce(arr, op, root_rank, timeout_ms))

    def broadcast(self, arr, root_rank: int, timeout_ms: int):
        return self._delegate(
            timeout_ms, lambda g: g.broadcast(arr, root_rank, timeout_ms))

    def allgather(self, arr, timeout_ms: int) -> List[np.ndarray]:
        return self._delegate(
            timeout_ms, lambda g: g.allgather(arr, timeout_ms))

    def reducescatter(self, arr, op: ReduceOp, timeout_ms: int) -> np.ndarray:
        return self._delegate(
            timeout_ms, lambda g: g.reducescatter(arr, op, timeout_ms))

    def barrier(self, timeout_ms: int) -> None:
        self._delegate(timeout_ms, lambda g: g.barrier(timeout_ms))

    def send(self, arr, dst_rank: int, timeout_ms: int) -> None:
        self._delegate(
            timeout_ms, lambda g: g.send(arr, dst_rank, timeout_ms))

    def recv(self, src_rank: int, timeout_ms: int) -> np.ndarray:
        return self._delegate(
            timeout_ms, lambda g: g.recv(src_rank, timeout_ms))

    def destroy(self) -> None:
        if self._runner is not None:
            # fail in-flight handles FIRST; the transport teardown below
            # is what unblocks a reducer parked mid-round
            try:
                self._runner.shutdown()
            except Exception:
                logger.debug("collective runner shutdown failed",
                             exc_info=True)
        if self._impl is not None:
            try:
                self._impl.destroy()
            except Exception:
                logger.debug("collective impl destroy failed", exc_info=True)
        _sweep_group_keys(self.group_name)


class XlaGroup(BaseGroup):
    """Device-plane collectives: jitted XLA programs over a one-axis mesh.

    Each rank is one *process* of a shared `jax.distributed` runtime (for
    world_size == 1, plain local JAX). The mesh takes one device per process;
    a collective builds a global array with each process's contribution as its
    addressable shard and jits the reduction with a replicated out-sharding,
    so XLA emits the all-reduce/all-gather over ICI/DCN.
    """

    def __init__(
        self,
        world_size: int,
        rank: int,
        group_name: str,
        *,
        coordinator: Optional[str] = None,
    ):
        super().__init__(world_size, rank, group_name)
        import jax

        # The is_initialized() check must come before ANY backend-touching JAX
        # call (process_count() would initialize XLA and make
        # distributed.initialize() unconstructible).
        if world_size > 1 and not jax.distributed.is_initialized():
            # Join (or start) the shared distributed runtime. Rank 0 publishes
            # the coordinator endpoint through the controller KV.
            coord_key = f"{group_name}:coordinator"
            if coordinator is None:
                if rank == 0:
                    import socket

                    sock = socket.socket()
                    sock.bind(("", 0))
                    port = sock.getsockname()[1]
                    sock.close()
                    coordinator = f"{_node_ip()}:{port}"
                    _kv().kv_put(coord_key, coordinator, ns=_KV_NS)
                else:
                    deadline = time.monotonic() + 30
                    while coordinator is None:
                        coordinator = _kv().kv_get(coord_key, ns=_KV_NS)
                        if coordinator is None:
                            if time.monotonic() > deadline:
                                raise TimeoutError("no coordinator published")
                            time.sleep(0.05)
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=world_size,
                process_id=rank,
            )
        if jax.process_count() != world_size:
            raise RuntimeError(
                f"xla backend: jax.process_count()={jax.process_count()} but "
                f"world_size={world_size}; start one process per rank"
            )
        if world_size > 1 and jax.process_index() != rank:
            # The mesh below places each process's shard at its
            # process_index; a pre-initialized runtime whose rank assignment
            # differs would silently reorder broadcast/allgather results.
            raise RuntimeError(
                f"xla backend: jax.process_index()={jax.process_index()} "
                f"must equal the collective rank ({rank})"
            )
        self._jax = jax
        # one device per process, ordered by rank
        per_proc: Dict[int, Any] = {}
        for d in jax.devices():
            per_proc.setdefault(d.process_index, d)
        devs = [per_proc[i] for i in range(world_size)]
        from jax.sharding import Mesh

        self._mesh = Mesh(np.array(devs), ("ranks",))
        self._local_device = per_proc[jax.process_index()]
        # p2p side-channel (host data plane)
        self._host = HostGroup(world_size, rank, group_name + ":p2p") if world_size > 1 else None
        # One jitted program per op kind, reused across calls (jax.jit caches
        # by function identity — fresh lambdas per call would recompile).
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        replicated = NamedSharding(self._mesh, P())
        self._programs = {
            ReduceOp.SUM: jax.jit(lambda a: jnp.sum(a, axis=0), out_shardings=replicated),
            ReduceOp.MEAN: jax.jit(lambda a: jnp.mean(a, axis=0), out_shardings=replicated),
            ReduceOp.PRODUCT: jax.jit(lambda a: jnp.prod(a, axis=0), out_shardings=replicated),
            ReduceOp.MAX: jax.jit(lambda a: jnp.max(a, axis=0), out_shardings=replicated),
            ReduceOp.MIN: jax.jit(lambda a: jnp.min(a, axis=0), out_shardings=replicated),
            "identity": jax.jit(lambda a: a, out_shardings=replicated),
            "take": jax.jit(
                lambda a, i: jax.lax.dynamic_index_in_dim(a, i, keepdims=False),
                out_shardings=replicated,
            ),
        }

    def _global(self, x: np.ndarray):
        jax = self._jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = np.asarray(x)
        shard = jax.device_put(x[None], self._local_device)
        return jax.make_array_from_single_device_arrays(
            (self.world_size,) + x.shape,
            NamedSharding(self._mesh, P("ranks")),
            [shard],
        )

    def allreduce(self, arr, op: ReduceOp, timeout_ms: int,
                  out: Optional[np.ndarray] = None) -> np.ndarray:
        red = self._programs[op](self._global(arr))
        host = np.asarray(red.addressable_data(0))
        if out is not None:
            check_inplace_out(out, host)
            np.copyto(out.reshape(host.shape), host)
            return out
        return host

    def reduce(self, arr, op: ReduceOp, root_rank: int, timeout_ms: int):
        out = self.allreduce(arr, op, timeout_ms)
        return out if self.rank == root_rank else np.asarray(arr)

    def broadcast(self, arr, root_rank: int, timeout_ms: int):
        out = self._programs["take"](self._global(arr), root_rank)
        return np.asarray(out.addressable_data(0))

    def allgather(self, arr, timeout_ms: int) -> List[np.ndarray]:
        out = self._programs["identity"](self._global(arr))
        return list(np.asarray(out.addressable_data(0)))

    def reducescatter(self, arr, op: ReduceOp, timeout_ms: int) -> np.ndarray:
        full = self.allreduce(arr, op, timeout_ms)
        return np.array_split(full, self.world_size, axis=0)[self.rank]

    def barrier(self, timeout_ms: int) -> None:
        self.allreduce(np.zeros((1,), np.float32), ReduceOp.SUM, timeout_ms)

    def send(self, arr, dst_rank: int, timeout_ms: int) -> None:
        if self._host is None:
            raise RuntimeError("send/recv needs world_size > 1")
        self._host.send(arr, dst_rank, timeout_ms)

    def recv(self, src_rank: int, timeout_ms: int) -> np.ndarray:
        if self._host is None:
            raise RuntimeError("send/recv needs world_size > 1")
        return self._host.recv(src_rank, timeout_ms)

    def destroy(self) -> None:
        if self._host is not None:
            self._host.destroy()
        try:
            _kv().kv_del(f"{self.group_name}:coordinator", ns=_KV_NS)
        except Exception:
            pass


_BACKENDS = {Backend.HOST: HostGroup, Backend.XLA: XlaGroup}


# ------------------------------------------------------------- group manager


class GroupManager:
    def __init__(self):
        self._groups: Dict[str, BaseGroup] = {}
        self._lock = threading.Lock()

    def create(
        self,
        backend: Backend,
        world_size: int,
        rank: int,
        name: str,
        *,
        public_name: Optional[str] = None,
        algo: Optional[str] = None,
    ) -> BaseGroup:
        """`name` keys the wire protocol (KV keys); `public_name` (default:
        same) keys the local registry callers look groups up by."""
        with self._lock:
            key = public_name or name
            if key in self._groups:
                raise RuntimeError(f"collective group {key!r} already initialized")
            if backend is Backend.HOST:
                group: BaseGroup = HostGroup(world_size, rank, name,
                                             algo=algo)
            else:
                group = _BACKENDS[backend](world_size, rank, name)
            group._public_name = key
            self._groups[key] = group
            return group

    def get(self, name: str) -> Optional[BaseGroup]:
        with self._lock:
            return self._groups.get(name)

    def destroy(self, name: str) -> None:
        with self._lock:
            group = self._groups.pop(name, None)
        if group is not None:
            group.destroy()


_manager = GroupManager()


def _resolve_group(group_name: str) -> BaseGroup:
    group = _manager.get(group_name)
    if group is not None:
        # Steady state: trust the cached group — no per-call KV round-trip
        # (a stale declarative generation surfaces as a timeout whose
        # failure path re-validates via BaseGroup._raise_if_stale; wire
        # keys are generation-suffixed, so cross-generation traffic can
        # never silently mix).
        return group
    # Declarative path (≈ collective.py:151): the driver stored group metadata
    # in the controller KV keyed by group name; resolve our rank by actor id.
    meta = _kv().kv_get(f"decl:{group_name}", ns=_KV_NS)
    if meta is None:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized in this "
            "process; call init_collective_group or create_collective_group"
        )
    from ray_tpu._private.api import get_runtime_context

    my_actor = get_runtime_context().actor_id
    if my_actor is None or my_actor not in meta["actor_ids"]:
        raise RuntimeError(
            f"this process is not a member of collective group {group_name!r}"
        )
    rank = meta["ranks"][meta["actor_ids"].index(my_actor)]
    group = _manager.create(
        Backend.parse(meta["backend"]),
        meta["world_size"],
        rank,
        # Key the wire protocol by generation so a stale member erring out is
        # a timeout, never a silent cross-generation mix.
        f"{group_name}@{meta['gen']}",
        public_name=group_name,
    )
    group._decl_gen = meta["gen"]
    return group


# ------------------------------------------------------------- public API


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "host",
    group_name: str = "default",
    *,
    algo: Optional[str] = None,
) -> None:
    """Imperative init, called inside each participating task/actor
    (≈ collective.py:120). ``algo`` (host backend only) forces the data
    path: auto/shm/ring/kv — default ``RAY_TPU_COLLECTIVE_ALGO``."""
    _manager.create(Backend.parse(backend), world_size, rank, group_name,
                    algo=algo)


def create_collective_group(
    actors: Sequence[Any],
    world_size: int,
    ranks: Sequence[int],
    backend: str = "host",
    group_name: str = "default",
) -> None:
    """Declarative init from the driver over actor handles
    (≈ collective.py:151): stores membership in the controller KV; each actor
    resolves its rank lazily on its first collective call."""
    if len(actors) != len(ranks) or len(actors) != world_size:
        raise ValueError("need exactly world_size actors and ranks")
    if sorted(ranks) != list(range(world_size)):
        raise ValueError(f"ranks must be a permutation of 0..{world_size - 1}")
    actor_ids = [a._actor_id.hex() for a in actors]
    # The generation counter lives under its own key that destroy_* never
    # deletes: re-creating a destroyed group must still advance the gen, or
    # stale members (and their leftover wire keys from the old generation)
    # would silently mix into the new group.
    prev_gen = _kv().kv_get(f"declgen:{group_name}", ns=_KV_NS)
    gen = (prev_gen + 1) if prev_gen is not None else 0
    _kv().kv_put(f"declgen:{group_name}", gen, ns=_KV_NS)
    _kv().kv_put(
        f"decl:{group_name}",
        {
            "world_size": world_size,
            "ranks": list(ranks),
            "backend": str(Backend.parse(backend).value),
            "actor_ids": actor_ids,
            "gen": gen,
        },
        ns=_KV_NS,
    )


def is_group_initialized(group_name: str = "default") -> bool:
    return _manager.get(group_name) is not None


def destroy_collective_group(group_name: str = "default") -> None:
    _manager.destroy(group_name)
    try:
        _kv().kv_del(f"decl:{group_name}", ns=_KV_NS)
    except Exception:
        pass


def get_rank(group_name: str = "default") -> int:
    return _resolve_group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _resolve_group(group_name).world_size


DEFAULT_TIMEOUT_MS = 30000


def allreduce(
    tensor,
    group_name: str = "default",
    op: ReduceOp = ReduceOp.SUM,
    timeout_ms: int = DEFAULT_TIMEOUT_MS,
):
    """Allreduce across the group (returns the reduced array; ≈ collective.py:258)."""
    return _resolve_group(group_name).allreduce(tensor, op, timeout_ms)


def allreduce_coalesced(
    tensors: Sequence[Any],
    group_name: str = "default",
    op: ReduceOp = ReduceOp.SUM,
    timeout_ms: int = DEFAULT_TIMEOUT_MS,
    bucket_bytes: Optional[int] = None,
    out: Optional[Sequence[Any]] = None,
) -> List[np.ndarray]:
    """Allreduce a list of tensors in same-dtype buckets (one collective
    round per bucket). The bucketed twin of torch's
    ``allreduce_coalesced`` — what the RLlib learner uses for its
    gradient tree instead of one monolithic concatenate. ``out=``
    (persistent arrays matching the input shapes/dtypes) makes a
    steady-state call allocation-free; ``op=ReduceOp.MEAN`` pre-scales
    into the pack copy, so no per-leaf divide pass exists."""
    return _resolve_group(group_name).allreduce_coalesced(
        tensors, op, timeout_ms, bucket_bytes, out=out)


def allreduce_coalesced_async(
    tensors: Sequence[Any],
    group_name: str = "default",
    op: ReduceOp = ReduceOp.SUM,
    timeout_ms: int = DEFAULT_TIMEOUT_MS,
    bucket_bytes: Optional[int] = None,
    out: Optional[Sequence[Any]] = None,
    overlap: Optional[bool] = None,
    on_bucket=None,
):
    """Overlapped coalesced allreduce — returns a ``CollectiveWork``
    handle (``.wait()``/``.done()``) immediately and hides the host-side
    gradient movement behind device compute: the group's runner
    materializes buckets (one batched ``jax.device_get`` each, reverse-
    backward order) and pipelines their shm/ring reduce rounds. Device
    arrays are accepted directly — do NOT ``np.asarray`` the leaves
    first, that would serialize the transfers this API exists to
    overlap. ``on_bucket(indices, arrays)`` (optional) fires exactly
    once per coalesced bucket THE MOMENT its reduce lands — on the
    runner's reducer thread — with the input indices of the bucket's
    tensors and their reduced arrays, so per-bucket downstream work
    (the pipeline trainer's fused optimizer apply) overlaps the
    remaining buckets' rounds; a callback exception poisons the group
    like any mid-round failure. ``overlap`` forces the path (None = the
    ``RAY_TPU_COLLECTIVE_OVERLAP`` knob); the sync fallback returns an
    already-completed handle and still fires ``on_bucket`` per bucket
    on the caller's thread, so call sites stay identical."""
    from ray_tpu.util.collective.async_work import validate_on_bucket

    # misuse fails HERE, before group resolution: a bad callback must
    # raise at the call site, not poison the group from the runner
    validate_on_bucket(on_bucket)
    return _resolve_group(group_name).allreduce_coalesced_async(
        tensors, op, timeout_ms, bucket_bytes, out=out, overlap=overlap,
        on_bucket=on_bucket)


def reduce(
    tensor,
    dst_rank: int = 0,
    group_name: str = "default",
    op: ReduceOp = ReduceOp.SUM,
    timeout_ms: int = DEFAULT_TIMEOUT_MS,
):
    return _resolve_group(group_name).reduce(tensor, op, dst_rank, timeout_ms)


def broadcast(
    tensor,
    src_rank: int = 0,
    group_name: str = "default",
    timeout_ms: int = DEFAULT_TIMEOUT_MS,
):
    return _resolve_group(group_name).broadcast(tensor, src_rank, timeout_ms)


def allgather(
    tensor, group_name: str = "default", timeout_ms: int = DEFAULT_TIMEOUT_MS
) -> List[np.ndarray]:
    return _resolve_group(group_name).allgather(tensor, timeout_ms)


def reducescatter(
    tensor,
    group_name: str = "default",
    op: ReduceOp = ReduceOp.SUM,
    timeout_ms: int = DEFAULT_TIMEOUT_MS,
):
    return _resolve_group(group_name).reducescatter(tensor, op, timeout_ms)


def send(
    tensor, dst_rank: int, group_name: str = "default", timeout_ms: int = DEFAULT_TIMEOUT_MS
) -> None:
    _resolve_group(group_name).send(tensor, dst_rank, timeout_ms)


def recv(
    src_rank: int, group_name: str = "default", timeout_ms: int = DEFAULT_TIMEOUT_MS
) -> np.ndarray:
    """Receive from src_rank. (The reference mutates a passed-in tensor; we
    return the received array — functional style, consistent with JAX.)"""
    return _resolve_group(group_name).recv(src_rank, timeout_ms)


def barrier(group_name: str = "default", timeout_ms: int = DEFAULT_TIMEOUT_MS) -> None:
    _resolve_group(group_name).barrier(timeout_ms)


def synchronize(group_name: str = "default") -> None:
    """Block until all queued device work is done (≈ cuda synchronize)."""
    try:
        import jax

        (jax.device_put(0.0) + 0).block_until_ready()
    except Exception:
        pass
